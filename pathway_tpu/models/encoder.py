"""Flax transformer encoders: bi-encoder (SentenceTransformer-class) and
cross-encoder (reranker-class).

This is the TPU execution path the north star asks for: the reference wraps
host-side sentence-transformers/CrossEncoder models in UDFs
(``xpacks/llm/embedders.py:85-401``, ``rerankers.py:58-322``); here the
models are jit-compiled Flax modules with bucketed static shapes so
streaming row deltas hit a warm XLA cache.

Architectures mirror the reference's default checkpoints:
  * all-MiniLM-L6-v2 : 6 layers, hidden 384, 12 heads, ffn 1536, vocab 30522
  * bge-base-en-v1.5 : 12 layers, hidden 768, 12 heads, ffn 3072
  * ms-marco-MiniLM-L-6-v2 cross-encoder: MiniLM trunk + scalar head
Tokenizers load from a local HuggingFace cache when present; model weights
are deterministic random init in this environment (zero egress — no
checkpoint downloads), which keeps shapes/FLOPs identical: throughput and
latency on TPU are weight-independent.  ``load_hf_weights`` maps a locally
cached ``transformers`` BERT-family checkpoint into the Flax params when
one is available.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from flax import linen as nn

from pathway_tpu.models.tokenizer import (
    bucket_seq_len,
    load_tokenizer,
    pad_batch,
)
from pathway_tpu.ops.attention import encoder_attention


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden: int = 384
    layers: int = 6
    heads: int = 12
    intermediate: int = 1536
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    # sentence-embedding pooling: "mean" (MiniLM family) or "cls" (BGE
    # family) — mirrors the pooling module sentence-transformers reads
    # from the checkpoint (reference embedders.py:270 delegates to it)
    pooling: str = "mean"


PRESETS: dict[str, EncoderConfig] = {
    "all-MiniLM-L6-v2": EncoderConfig(),
    "sentence-transformers/all-MiniLM-L6-v2": EncoderConfig(),
    "BAAI/bge-base-en-v1.5": EncoderConfig(
        hidden=768, layers=12, intermediate=3072, pooling="cls"
    ),
    "bge-base-en-v1.5": EncoderConfig(
        hidden=768, layers=12, intermediate=3072, pooling="cls"
    ),
    "BAAI/bge-small-en-v1.5": EncoderConfig(layers=12, pooling="cls"),
    "cross-encoder/ms-marco-MiniLM-L-6-v2": EncoderConfig(),
    "mixedbread-ai/mxbai-embed-large-v1": EncoderConfig(
        hidden=1024, layers=24, heads=16, intermediate=4096, pooling="cls"
    ),
}


def config_for(model_name: str) -> EncoderConfig:
    """Preset lookup, or — for a local checkpoint directory — the shape
    read from its ``config.json`` (any BERT-family ``transformers`` save),
    with the pooling mode taken from a sentence-transformers ``1_Pooling``
    module config when one is present."""
    import json
    import os

    if model_name in PRESETS:
        return PRESETS[model_name]
    cfg_path = os.path.join(model_name, "config.json")
    if os.path.isfile(cfg_path):
        with open(cfg_path) as f:
            hf = json.load(f)
        pooling = "mean"
        pool_path = os.path.join(model_name, "1_Pooling", "config.json")
        if os.path.isfile(pool_path):
            with open(pool_path) as f:
                pool_cfg = json.load(f)
            if pool_cfg.get("pooling_mode_cls_token"):
                pooling = "cls"
        return EncoderConfig(
            vocab_size=hf.get("vocab_size", 30522),
            hidden=hf.get("hidden_size", 384),
            layers=hf.get("num_hidden_layers", 6),
            heads=hf.get("num_attention_heads", 12),
            intermediate=hf.get("intermediate_size", 1536),
            max_len=hf.get("max_position_embeddings", 512),
            pooling=pooling,
        )
    return EncoderConfig()


class TransformerBlock(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.config
        attn_out = nn.MultiHeadDotProductAttention(
            num_heads=cfg.heads,
            qkv_features=cfg.hidden,
            dtype=cfg.dtype,
            deterministic=True,
        )(x, x, mask=mask)
        # exact (erf) gelu and 1e-12 LN eps match BERT-family checkpoints;
        # the module tree is the numerical source of truth the golden
        # parity suite checks against torch (tests/test_model_parity.py)
        x = nn.LayerNorm(dtype=cfg.dtype, epsilon=1e-12)(x + attn_out)
        h = nn.Dense(cfg.intermediate, dtype=cfg.dtype)(x)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(cfg.hidden, dtype=cfg.dtype)(h)
        return nn.LayerNorm(dtype=cfg.dtype, epsilon=1e-12)(x + h)


class Encoder(nn.Module):
    """BERT-style trunk producing token representations."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask):
        cfg = self.config
        positions = jnp.arange(input_ids.shape[1])[None, :]
        tok = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype)(input_ids)
        pos = nn.Embed(cfg.max_len, cfg.hidden, dtype=cfg.dtype)(positions)
        x = nn.LayerNorm(dtype=cfg.dtype, epsilon=1e-12)(tok + pos)
        # [batch, 1, 1, seq] additive-style boolean mask for attention
        attn_mask = attention_mask[:, None, None, :].astype(bool)
        for _ in range(cfg.layers):
            x = TransformerBlock(cfg)(x, attn_mask)
        return x


def _pool(x, attention_mask, pooling: str):
    """Masked mean or CLS pooling of token reps ``[B, S, H]`` → f32 [B, H]."""
    if pooling == "cls":
        return x[:, 0, :].astype(jnp.float32)
    m = attention_mask[:, :, None].astype(x.dtype)
    pooled = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
    return pooled.astype(jnp.float32)


class SentenceEncoderModule(nn.Module):
    """Trunk + masked pooling + L2 normalization → sentence embedding."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask):
        x = Encoder(self.config)(input_ids, attention_mask)
        pooled = _pool(x, attention_mask, self.config.pooling)
        return pooled / (jnp.linalg.norm(pooled, axis=1, keepdims=True) + 1e-12)


class CrossEncoderModule(nn.Module):
    """Trunk + CLS head → relevance score per (query, doc) pair."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask):
        x = Encoder(self.config)(input_ids, attention_mask)
        cls = x[:, 0, :].astype(jnp.float32)
        h = nn.Dense(self.config.hidden, dtype=jnp.float32)(cls)
        h = jnp.tanh(h)
        return nn.Dense(1, dtype=jnp.float32)(h)[:, 0]


# ---------------------------------------------------------------------------
# Fused inference path.
#
# The Flax modules above are the parameter-structure source of truth (init,
# checkpoint mapping, training).  For the streaming hot path the same params
# are repacked once into a flat bf16 tree (QKV kernels concatenated into one
# [H, 3H] matmul operand) and run through a hand-scheduled forward: 2D
# [B*S, H] activations end to end (no relayout copies) with attention in the
# pallas kernel (`ops/attention.py`).  Measured on v5e this is ~3x the
# throughput of the stock module.apply lowering at MiniLM shapes.
# ---------------------------------------------------------------------------


def pack_fast_params(params, config: EncoderConfig):
    """Repack a module param tree into the flat bf16 tree the fused forward
    consumes.  Works for both SentenceEncoderModule and CrossEncoderModule
    trees (the latter adds the scoring head)."""
    p = params["params"]
    enc = p["Encoder_0"] if "Encoder_0" in p else p
    H = config.hidden

    def bf(x):
        return jnp.asarray(x, jnp.bfloat16)

    layers = []
    for i in range(config.layers):
        blk = enc[f"TransformerBlock_{i}"]
        att = blk["MultiHeadDotProductAttention_0"]
        qkv_k = jnp.concatenate(
            [att[n]["kernel"].reshape(H, H) for n in ("query", "key", "value")],
            axis=1,
        )
        qkv_b = jnp.concatenate(
            [att[n]["bias"].reshape(H) for n in ("query", "key", "value")]
        )
        layers.append(
            dict(
                qkv_k=bf(qkv_k),
                qkv_b=bf(qkv_b),
                out_k=bf(att["out"]["kernel"].reshape(H, H)),
                out_b=bf(att["out"]["bias"]),
                ln0_s=bf(blk["LayerNorm_0"]["scale"]),
                ln0_b=bf(blk["LayerNorm_0"]["bias"]),
                ff1_k=bf(blk["Dense_0"]["kernel"]),
                ff1_b=bf(blk["Dense_0"]["bias"]),
                ff2_k=bf(blk["Dense_1"]["kernel"]),
                ff2_b=bf(blk["Dense_1"]["bias"]),
                ln1_s=bf(blk["LayerNorm_1"]["scale"]),
                ln1_b=bf(blk["LayerNorm_1"]["bias"]),
            )
        )
    tree = dict(
        emb_word=bf(enc["Embed_0"]["embedding"]),
        emb_pos=bf(enc["Embed_1"]["embedding"]),
        eln_s=bf(enc["LayerNorm_0"]["scale"]),
        eln_b=bf(enc["LayerNorm_0"]["bias"]),
        layers=layers,
    )
    if "Dense_0" in p:  # cross-encoder scoring head (kept in f32, tiny)
        tree["head"] = dict(
            d0_k=jnp.asarray(p["Dense_0"]["kernel"], jnp.float32),
            d0_b=jnp.asarray(p["Dense_0"]["bias"], jnp.float32),
            d1_k=jnp.asarray(p["Dense_1"]["kernel"], jnp.float32),
            d1_b=jnp.asarray(p["Dense_1"]["bias"], jnp.float32),
        )
    return tree


def quantize_encoder_tree(tree):
    """W8A8 serving tree: the four big matmul weights per layer become
    ``{"q": int8, "s": f32 per-output-channel}``; biases, layernorms,
    embeddings, and the attention kernel stay bf16.

    On v5e-class TPUs the MXU runs int8×int8 at TWICE the bf16 peak, and
    the encoder headline is compute-bound (BGE ~0.6 MFU), so this is the
    path past bf16 throughput — at the cost of int8 activation rounding
    (per-token dynamic scales; embedding fidelity pinned by tests and the
    bench reports cosine agreement alongside throughput).
    """

    def quant(w):
        w32 = jnp.asarray(w, jnp.float32)
        s = jnp.max(jnp.abs(w32), axis=-2, keepdims=True) / 127.0
        s = jnp.maximum(s, 1e-12)
        q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
        return {"q": q, "s": s}

    layers = [
        {
            **lp,
            "qkv_k": quant(lp["qkv_k"]),
            "out_k": quant(lp["out_k"]),
            "ff1_k": quant(lp["ff1_k"]),
            "ff2_k": quant(lp["ff2_k"]),
        }
        for lp in tree["layers"]
    ]
    return {**tree, "layers": layers}


def _qdot(x, w):
    """``x @ w`` where ``w`` may be a W8A8 pair: activations quantize
    per-token (dynamic symmetric, one max-reduce), the dot runs
    int8×int8→int32 on the MXU, and the two scales multiply the output.
    Falls through to the plain bf16 dot for float weights."""
    if not (isinstance(w, dict) and "q" in w):
        return x @ w
    s_x = jnp.max(jnp.abs(x), axis=-1, keepdims=True).astype(jnp.float32) / 127.0
    s_x = jnp.maximum(s_x, 1e-8)
    xq = jnp.clip(
        jnp.round(x.astype(jnp.float32) / s_x), -127, 127
    ).astype(jnp.int8)
    acc = jax.lax.dot(xq, w["q"], preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * s_x * w["s"]).astype(x.dtype)


def _ln(x, scale, bias, eps: float = 1e-6):
    """LayerNorm with f32 statistics computed on the MXU.

    XLA lowers the conventional convert-to-f32 + reduce as a strided
    `convert_reduce` fusion that costs ~0.25 ms per call at [32k, 384] on
    v5e — more than the matmuls around it.  Instead, both statistics come
    from bf16 matmuls against a ones-vector with f32 accumulation: first
    sum(x) for the mean, then sum((x-mean)^2) on the *centered* values for
    the variance.  Centering before squaring matters: the one-pass
    E[x^2]-E[x]^2 form catastrophically cancels under bf16 rounding when a
    row's |mean| dominates its spread (near-constant rows), which this
    two-pass form avoids.  Measured +13% end-to-end encoder throughput vs
    the reduce formulation.
    """
    shape = x.shape
    H = shape[-1]
    x2 = x.reshape(-1, H)
    ones = jnp.ones((H, 1), x.dtype)
    s1 = jax.lax.dot(x2, ones, preferred_element_type=jnp.float32)
    mean = s1 / H
    xc = x2.astype(jnp.float32) - mean
    xcb = xc.astype(x.dtype)
    s2 = jax.lax.dot(xcb * xcb, ones, preferred_element_type=jnp.float32)
    var = s2 / H
    y = (xc * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    return y.reshape(shape) * scale + bias


def fused_trunk(tree, input_ids, attention_mask, config: EncoderConfig, *, interpret=False):
    """BERT trunk over the packed tree; returns token reps ``[B, S, H]``."""
    B, S = input_ids.shape
    H = config.hidden
    x = tree["emb_word"][input_ids] + tree["emb_pos"][:S][None, :, :]
    x = _ln(x, tree["eln_s"], tree["eln_b"]).reshape(B * S, H)
    bias = jnp.where(attention_mask > 0, 0.0, -1e9).astype(jnp.float32)  # [B, S]
    for lp in tree["layers"]:
        qkv = _qdot(x, lp["qkv_k"]) + lp["qkv_b"]  # [B*S, 3H]
        ctx = encoder_attention(
            qkv[:, :H].reshape(B, S, H),
            qkv[:, H : 2 * H].reshape(B, S, H),
            qkv[:, 2 * H :].reshape(B, S, H),
            bias,
            config.heads,
            interpret=interpret,
        ).reshape(B * S, H)
        x = _ln(x + _qdot(ctx, lp["out_k"]) + lp["out_b"], lp["ln0_s"], lp["ln0_b"])
        h = jax.nn.gelu(_qdot(x, lp["ff1_k"]) + lp["ff1_b"], approximate=True)
        x = _ln(x + _qdot(h, lp["ff2_k"]) + lp["ff2_b"], lp["ln1_s"], lp["ln1_b"])
    return x.reshape(B, S, H)


def fused_sentence_apply(tree, input_ids, attention_mask, config: EncoderConfig, *, interpret=False):
    """Fused equivalent of ``SentenceEncoderModule.apply``."""
    x = fused_trunk(tree, input_ids, attention_mask, config, interpret=interpret)
    pooled = _pool(x, attention_mask, config.pooling)
    return pooled / (jnp.linalg.norm(pooled, axis=1, keepdims=True) + 1e-12)


def fused_cross_apply(tree, input_ids, attention_mask, config: EncoderConfig, *, interpret=False):
    """Fused equivalent of ``CrossEncoderModule.apply``."""
    x = fused_trunk(tree, input_ids, attention_mask, config, interpret=interpret)
    head = tree["head"]
    cls = x[:, 0, :].astype(jnp.float32)
    h = jnp.tanh(cls @ head["d0_k"] + head["d0_b"])
    return (h @ head["d1_k"] + head["d1_b"])[:, 0]


def load_hf_weights(model_name: str, params, config: EncoderConfig):
    """Map a locally cached ``transformers`` BERT-family checkpoint onto the
    Flax param tree; returns the updated tree or ``None`` when no local
    checkpoint exists (zero-egress environments keep random init).

    Token-type embeddings (always type 0 here) are folded into the word
    embedding table so the architectures match exactly.  Cross-encoder
    trees (scoring head at the tree root) load through
    ``AutoModelForSequenceClassification`` so the pooler + classifier map
    onto the head denses (matching the reference's CrossEncoder,
    ``xpacks/llm/rerankers.py:58``).
    """
    import os

    os.environ.setdefault("HF_HUB_OFFLINE", "1")
    tree_root = params["params"]
    has_head = "Dense_0" in tree_root and "Encoder_0" in tree_root
    try:
        if has_head:
            from transformers import AutoModelForSequenceClassification

            hf = AutoModelForSequenceClassification.from_pretrained(
                model_name, local_files_only=True
            )
        else:
            from transformers import AutoModel  # noqa: PLC0415

            hf = AutoModel.from_pretrained(model_name, local_files_only=True)
    except Exception:
        return None

    sd = {k: v.detach().cpu().numpy() for k, v in hf.state_dict().items()}
    # *ForSequenceClassification prefixes the trunk with the model type
    sd = {
        (k[5:] if k.startswith("bert.") else k): v for k, v in sd.items()
    }
    prefix = "encoder." if any(k.startswith("encoder.layer") for k in sd) else ""
    # the checkpoint's layer count must match the config exactly: mapping
    # only a prefix of a deeper trunk would silently truncate the model
    ckpt_layers = 1 + max(
        (
            int(k.split("layer.")[1].split(".")[0])
            for k in sd
            if "layer." in k
        ),
        default=-1,
    )
    if ckpt_layers != config.layers:
        return None
    h, heads = config.hidden, config.heads
    hd = h // heads

    import copy

    new_params = copy.deepcopy(jax.device_get(params))

    def put(path_parts, value):
        # navigate the mutable dict-of-dicts copy
        cur = new_params["params"]
        for part in path_parts[:-1]:
            cur = cur[part]
        expect = cur[path_parts[-1]].shape
        if tuple(value.shape) != tuple(expect):
            raise ValueError(f"{path_parts}: shape {value.shape} != {expect}")
        cur[path_parts[-1]] = value.astype(np.float32)

    try:
        enc = ["Encoder_0"] if "Encoder_0" in new_params["params"] else []
        word = sd["embeddings.word_embeddings.weight"]
        type0 = sd["embeddings.token_type_embeddings.weight"][0]
        put(enc + ["Embed_0", "embedding"], word + type0[None, :])
        put(
            enc + ["Embed_1", "embedding"],
            sd["embeddings.position_embeddings.weight"][: config.max_len],
        )
        put(enc + ["LayerNorm_0", "scale"], sd["embeddings.LayerNorm.weight"])
        put(enc + ["LayerNorm_0", "bias"], sd["embeddings.LayerNorm.bias"])
        for i in range(config.layers):
            blk = enc + [f"TransformerBlock_{i}"]
            lp = f"{prefix}layer.{i}." if prefix else f"encoder.layer.{i}."
            attn = blk + ["MultiHeadDotProductAttention_0"]
            for name, hf_name in (("query", "query"), ("key", "key"), ("value", "value")):
                w = sd[f"{lp}attention.self.{hf_name}.weight"]
                b = sd[f"{lp}attention.self.{hf_name}.bias"]
                put(attn + [name, "kernel"], w.T.reshape(h, heads, hd))
                put(attn + [name, "bias"], b.reshape(heads, hd))
            wo = sd[f"{lp}attention.output.dense.weight"]
            put(attn + ["out", "kernel"], wo.T.reshape(heads, hd, h))
            put(attn + ["out", "bias"], sd[f"{lp}attention.output.dense.bias"])
            put(blk + ["LayerNorm_0", "scale"], sd[f"{lp}attention.output.LayerNorm.weight"])
            put(blk + ["LayerNorm_0", "bias"], sd[f"{lp}attention.output.LayerNorm.bias"])
            put(blk + ["Dense_0", "kernel"], sd[f"{lp}intermediate.dense.weight"].T)
            put(blk + ["Dense_0", "bias"], sd[f"{lp}intermediate.dense.bias"])
            put(blk + ["Dense_1", "kernel"], sd[f"{lp}output.dense.weight"].T)
            put(blk + ["Dense_1", "bias"], sd[f"{lp}output.dense.bias"])
            put(blk + ["LayerNorm_1", "scale"], sd[f"{lp}output.LayerNorm.weight"])
            put(blk + ["LayerNorm_1", "bias"], sd[f"{lp}output.LayerNorm.bias"])
        if has_head and "classifier.weight" in sd:
            put(["Dense_0", "kernel"], sd["pooler.dense.weight"].T)
            put(["Dense_0", "bias"], sd["pooler.dense.bias"])
            put(["Dense_1", "kernel"], sd["classifier.weight"].T)
            put(["Dense_1", "bias"], sd["classifier.bias"])
    except (KeyError, ValueError):
        return None
    return new_params


def init_model_params(module, model_name: str, config: EncoderConfig, seed: int = 0):
    """Deterministic init + local-checkpoint load: the ONE weight-loading
    sequence shared by the single-chip and long-context encoders.

    Returns ``(params, pretrained)``.
    """
    params = module.init(
        jax.random.PRNGKey(seed),
        jnp.zeros((1, 16), jnp.int32),
        jnp.ones((1, 16), jnp.int32),
    )
    loaded = load_hf_weights(model_name, params, config)
    if loaded is not None:
        return jax.tree_util.tree_map(jnp.asarray, loaded), True
    return params, False


class _JitModel:
    """Shared machinery: init params, bucket shapes, one DeviceExecutor
    registration per model instance (the executor owns jit + batch
    bucketing + compile-cache discipline — docs/device_executor.md)."""

    def __init__(self, module_cls, model_name: str, seed: int = 0,
                 max_batch: int = 512, quantize: str | None = None):
        import os

        self.config = config_for(model_name)
        self.model_name = model_name
        self.module = module_cls(self.config)
        self.tokenizer = load_tokenizer(
            model_name, self.config.vocab_size, self.config.max_len
        )
        self.max_batch = max_batch
        self.params, self.pretrained = init_model_params(
            self.module, model_name, self.config, seed
        )
        # Fused inference path (packed bf16 weights + pallas attention);
        # PATHWAY_FUSED_ENCODER=0 falls back to the stock module lowering.
        # `_infer_params` is whatever tree `_apply` consumes, so weight
        # updates flow through `set_params` on either path.
        from pathway_tpu.internals.config import env_bool, env_str

        self._fused = env_bool("PATHWAY_FUSED_ENCODER")
        # PATHWAY_ENCODER_QUANTIZE=int8 (or quantize="int8") switches the
        # fused path to W8A8 matmuls — 2x the MXU peak on v5e-class chips,
        # embedding fidelity pinned by tests/test_quantized_encoder.py.
        # The env default applies to sentence EMBEDDERS only: reranker
        # score fidelity is not pinned, so CrossEncoder quantizes only by
        # explicit per-instance opt-in.
        env_q = (
            None
            if module_cls is CrossEncoderModule
            else env_str("PATHWAY_ENCODER_QUANTIZE")
        )
        self._quantize = quantize or env_q or None
        if self._quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', got {self._quantize!r}")
        if self._quantize and not self._fused:
            raise ValueError("quantize='int8' requires the fused encoder path")
        if self._fused:
            fused = (
                fused_cross_apply
                if module_cls is CrossEncoderModule
                else fused_sentence_apply
            )
            cfg = self.config
            self._infer_params = self._pack(self.params)
            traceable = lambda tree, ids, mask: fused(tree, ids, mask, cfg)  # noqa: E731
        else:
            self._infer_params = self.params
            traceable = lambda params, ids, mask: self.module.apply(  # noqa: E731
                params, ids, mask
            )
        from pathway_tpu.device import BucketPolicy, get_default_executor

        # keyed by everything the traceable closes over (module class,
        # config via model_name, fused mode, bucket policy): a re-created
        # instance REPLACES the registration (old closure + compile cache
        # drop) instead of growing the process-global executor forever.
        # No donation: the raw `_apply` wrapper is a public surface whose
        # callers (benchmarks) legitimately reuse device arrays across
        # calls — donating would delete their buffers on non-CPU backends.
        self._executor = get_default_executor()
        self._callable = self._executor.register(
            f"encoder:{module_cls.__name__}:{model_name}"
            f":b{self.max_batch}:f{int(self._fused)}",
            traceable,
            policy=BucketPolicy(max_bucket=self.max_batch),
        )

    def _pack(self, params):
        tree = pack_fast_params(params, self.config)
        if self._quantize == "int8":
            tree = quantize_encoder_tree(tree)
        return tree

    def set_params(self, params) -> None:
        """Replace model weights (both the module tree and the fused tree)."""
        self.params = params
        self._infer_params = self._pack(params) if self._fused else params

    def n_params(self) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))

    @property
    def _apply(self):
        """The raw compiled wrapper (pre-padded fixed shapes only) — kept
        for benchmarks that bypass tokenization; streaming traffic goes
        through :meth:`_run_padded` → ``DeviceExecutor.run_batch``."""
        return self._executor.jitted(self._callable)

    def warmup(self, *, seq_lens: tuple[int, ...] = (), buckets=None) -> int:
        """Pay every (batch bucket × seq bucket) compile before traffic;
        returns the number of cache keys compiled."""
        seq_lens = seq_lens or (bucket_seq_len(self.config.max_len),)
        compiled = 0
        for seq in seq_lens:
            compiled += self._executor.warmup(
                self._callable,
                row_shapes=((seq,), (seq,)),
                dtypes=(np.int32, np.int32),
                operands=(self._infer_params,),
                buckets=buckets,
            )
        return compiled

    def _run_padded(self, id_lists: list[list[int]], max_length: int | None = None) -> np.ndarray:
        """Pad to the bucketed seq length and hand the ragged batch to
        the DeviceExecutor: it buckets/pads the batch axis, splits
        oversized batches, and dispatches on warm compiled shapes."""
        if not id_lists:
            return np.zeros((0,), dtype=np.float32)
        longest = max(len(x) for x in id_lists)
        seq = bucket_seq_len(min(longest, max_length or self.config.max_len))
        ids, mask = pad_batch(id_lists, seq)
        return self._executor.run_batch(
            self._callable, (ids, mask), operands=(self._infer_params,)
        )


class SentenceEncoder(_JitModel):
    """Text → normalized embedding vectors (device-batched)."""

    def __init__(self, model_name: str = "all-MiniLM-L6-v2", seed: int = 0,
                 max_batch: int = 512, quantize: str | None = None):
        super().__init__(SentenceEncoderModule, model_name, seed, max_batch, quantize)

    @property
    def dimensions(self) -> int:
        return self.config.hidden

    def encode(self, texts: list[str], max_length: int | None = None) -> np.ndarray:
        id_lists = [self.tokenizer.encode(t or "") for t in texts]
        return self._run_padded(id_lists, max_length)

    def encode_one(self, text: str) -> np.ndarray:
        return self.encode([text])[0]


class CrossEncoder(_JitModel):
    """(query, doc) pairs → relevance scores (device-batched)."""

    def __init__(
        self,
        model_name: str = "cross-encoder/ms-marco-MiniLM-L-6-v2",
        seed: int = 0,
        max_batch: int = 512,
        quantize: str | None = None,
    ):
        super().__init__(CrossEncoderModule, model_name, seed, max_batch, quantize)

    def score(self, pairs: list[tuple[str, str]], max_length: int | None = None) -> np.ndarray:
        id_lists = [self.tokenizer.encode_pair(q or "", d or "") for (q, d) in pairs]
        return self._run_padded(id_lists, max_length)


@functools.lru_cache(maxsize=8)
def shared_sentence_encoder(model_name: str = "all-MiniLM-L6-v2") -> SentenceEncoder:
    return SentenceEncoder(model_name)


@functools.lru_cache(maxsize=8)
def shared_cross_encoder(model_name: str = "cross-encoder/ms-marco-MiniLM-L-6-v2") -> CrossEncoder:
    return CrossEncoder(model_name)
