"""LoRA adapters for the decoder family (low-rank fine-tuning).

The reference consumes frozen checkpoints only; this framework trains,
and the standard way users adapt an LLM is LoRA: freeze the base
weights, learn a rank-``r`` update ``ΔW = a @ b`` per targeted matmul.
TPU-shaped by construction — the forward routes activations through the
bottleneck (``(x@a)@b``, two skinny matmuls) instead of materializing
dense deltas, the frozen base stays in whatever layout serving uses, and
adapter state (megabytes, not gigabytes) is what the optimizer carries
and the checkpointer saves.

``_mm`` in ``models/decoder.py`` recognises the ``{"w", "a", "b"}``
leaves, so LoRA trees run through prefill, chunked decode, and the
pipelined trunk unchanged.  Quantization and speculative decoding (which
builds an int8 draft internally) need plain trees — ``merge_lora`` the
adapters back into plain weights first; ``quantize_decoder_tree``
rejects adapted trees with that instruction.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pathway_tpu.models.decoder import DecoderConfig

# attention projections (+ optionally the dense MLP) — the usual targets;
# MoE expert weights go through the GShard einsums, not _mm, so they are
# rejected rather than silently left unadapted
DEFAULT_TARGETS = ("wq", "wv")
_ADAPTABLE = {"wq", "wk", "wv", "wo", "wg", "wu", "wd"}


def lora_decoder_tree(
    tree,
    cfg: DecoderConfig,
    *,
    rank: int = 8,
    alpha: float = 16.0,
    targets: tuple[str, ...] = DEFAULT_TARGETS,
    seed: int = 0,
):
    """Wrap ``targets`` layer weights as ``{"w", "a", "b"}`` LoRA leaves.

    ``a`` is scaled-normal, ``b`` zeros — the adapted model starts
    EXACTLY equal to the base (pinned by tests); ``alpha/rank`` is folded
    into ``a``'s init scale so the merged update is
    ``(alpha/rank) * a_raw @ b``.
    """
    unknown = set(targets) - _ADAPTABLE
    if unknown:
        raise ValueError(f"unknown LoRA targets {sorted(unknown)}")
    if cfg.experts and any(t in ("wg", "wu", "wd") for t in targets):
        raise ValueError(
            "LoRA on MoE expert MLP weights is not supported (they run "
            "through the GShard dispatch einsums); target the attention "
            "projections instead"
        )
    keys = jax.random.split(jax.random.PRNGKey(seed), len(targets))
    layers = dict(tree["layers"])
    for key, name in zip(keys, targets):
        w = layers[name]
        if isinstance(w, dict):
            raise ValueError(
                f"layer weight {name!r} is already wrapped ({sorted(w)}); "
                "LoRA applies to plain float trees"
            )
        H, O = w.shape[-2], w.shape[-1]
        a_shape = (*w.shape[:-1], rank)
        b_shape = (*w.shape[:-2], rank, O)
        scale = (alpha / rank) / np.sqrt(H)
        layers[name] = {
            "w": w,
            "a": (jax.random.normal(key, a_shape, jnp.float32) * scale).astype(
                w.dtype
            ),
            "b": jnp.zeros(b_shape, w.dtype),
        }
    return {**tree, "layers": layers}


def merge_lora(tree):
    """Fold every ``{"w", "a", "b"}`` leaf into a plain weight."""
    layers = {
        name: (
            (w["w"] + w["a"].astype(jnp.float32) @ w["b"].astype(jnp.float32)).astype(
                w["w"].dtype
            )
            if isinstance(w, dict) and "a" in w
            else w
        )
        for name, w in tree["layers"].items()
    }
    return {**tree, "layers": layers}


def lora_mask(tree):
    """Pytree of bools marking the trainable (adapter) leaves."""

    def mark(path, _leaf):
        return any(getattr(p, "key", None) in ("a", "b") for p in path)

    return jax.tree_util.tree_map_with_path(mark, tree)


def make_lora_train_step(
    cfg: DecoderConfig,
    base_tree,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    rank: int = 8,
    alpha: float = 16.0,
    targets: tuple[str, ...] = DEFAULT_TARGETS,
    moe_aux_weight: float = 0.01,
    seed: int = 0,
) -> tuple[Callable, Callable]:
    """Data-parallel LoRA fine-tuning of a frozen ``base_tree``.

    Weights replicate over the mesh (adapters are megabytes — dp is the
    right axis for LoRA) and the batch shards over ``data``; the
    optimizer is masked to the adapter leaves, so the base never moves
    and optimizer state is adapter-sized.  Returns ``(init_state, run)``
    compatible with ``TrainCheckpointer``.
    """
    from pathway_tpu.parallel.train import TrainState, make_lm_step_runner

    tree0 = lora_decoder_tree(
        base_tree, cfg, rank=rank, alpha=alpha, targets=targets, seed=seed
    )
    # multi_transform, NOT optax.masked: masked passes the complement's
    # updates through as raw gradients (ascent on the frozen base);
    # set_to_zero pins every non-adapter leaf
    labels = jax.tree_util.tree_map(
        lambda m: "train" if m else "freeze", lora_mask(tree0)
    )
    opt = optax.multi_transform(
        {"train": optimizer, "freeze": optax.set_to_zero()}, labels
    )

    def init_state() -> TrainState:
        replicated = NamedSharding(mesh, P())
        tree = jax.tree_util.tree_map(
            lambda t: jax.device_put(t, replicated), tree0
        )
        return TrainState(params=tree, opt_state=opt.init(tree))

    run = make_lm_step_runner(cfg, opt, mesh, moe_aux_weight=moe_aux_weight)
    return init_state, run
