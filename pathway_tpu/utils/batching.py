"""Async micro-batching: the streaming→device bridge.

The north star's key mechanism (BASELINE.json): "the Python-UDF bridge
batches row-deltas coming out of the dataflow into fixed-shape device
arrays so embed/rerank calls hit a warm XLA cache."  Embedder/reranker UDFs
are *async*: the engine's AsyncValuesNode launches one coroutine per row of
an epoch concurrently (§3.3 semantics), and this batcher coalesces all
concurrently-pending requests into large device batches.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Sequence


class AsyncMicroBatcher:
    """Coalesces concurrent async submissions into batched process calls.

    ``process_batch(items) -> results`` runs synchronously (typically a jit
    call).  Per-event-loop state: the engine may run each epoch under a fresh
    asyncio loop.
    """

    def __init__(
        self,
        process_batch: Callable[[list], Sequence],
        max_batch_size: int = 256,
        flush_delay: float = 0.002,
        run_in_thread: bool = False,
    ):
        """``run_in_thread=True`` runs each batch via ``asyncio.to_thread``
        so the event loop stays responsive during long device calls (LLM
        generation takes seconds; embedder batches take milliseconds and
        keep the default synchronous flush)."""
        self.process_batch = process_batch
        self.max_batch_size = max_batch_size
        self.flush_delay = flush_delay
        self.run_in_thread = run_in_thread
        self._per_loop: dict[int, list] = {}
        # strong refs: the loop only weak-refs tasks, and a GC'd batch
        # task would strand its futures forever
        self._tasks: set = set()

    async def submit(self, item: Any) -> Any:
        loop = asyncio.get_running_loop()
        key = id(loop)
        pending = self._per_loop.get(key)
        if pending is None:
            pending = self._per_loop[key] = []
            loop.create_task(self._flusher(key))
        future = loop.create_future()
        pending.append((item, future))
        if len(pending) >= self.max_batch_size:
            self._flush(key)
        return await future

    def _flush(self, key: int) -> None:
        pending = self._per_loop.get(key)
        if not pending:
            return
        batch = pending[: self.max_batch_size]
        del pending[: self.max_batch_size]
        if self.run_in_thread:
            task = asyncio.get_running_loop().create_task(
                self._run_batch_async(batch)
            )
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
        else:
            self._run_batch(batch)

    def _run_batch(self, batch: list) -> None:
        items = [it for (it, _f) in batch]
        try:
            results = self.process_batch(items)
            for (_it, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as exc:
            for _it, fut in batch:
                if not fut.done():
                    fut.set_exception(exc)

    async def _run_batch_async(self, batch: list) -> None:
        items = [it for (it, _f) in batch]
        try:
            results = await asyncio.to_thread(self.process_batch, items)
            for (_it, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as exc:  # noqa: BLE001 — deliver to every waiter
            for _it, fut in batch:
                if not fut.done():
                    fut.set_exception(exc)

    async def _flusher(self, key: int) -> None:
        # flush everything pending on this loop until it quiesces
        try:
            # first flush is IMMEDIATE: two zero-sleeps let every already-
            # scheduled same-tick submitter enqueue (the engine gathers an
            # epoch's rows in one tick), then the batch goes — a lone
            # serving query pays no fixed flush_delay latency.  Stragglers
            # that submit after awaiting something else are caught by the
            # flush_delay rounds below.
            await asyncio.sleep(0)
            await asyncio.sleep(0)
            while self._per_loop.get(key):
                self._flush(key)
            while True:
                await asyncio.sleep(self.flush_delay)
                pending = self._per_loop.get(key)
                if not pending:
                    break
                while self._per_loop.get(key):
                    self._flush(key)
        finally:
            self._per_loop.pop(key, None)
