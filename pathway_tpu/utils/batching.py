"""Async micro-batching: the streaming→device coalescing front-end.

The north star's key mechanism (BASELINE.json): "the Python-UDF bridge
batches row-deltas coming out of the dataflow into fixed-shape device
arrays so embed/rerank calls hit a warm XLA cache."  Embedder/reranker
UDFs are *async*: the engine's AsyncValuesNode launches one coroutine per
row of an epoch concurrently (§3.3 semantics), and this batcher coalesces
all concurrently-pending requests into large batches.

Since the DeviceExecutor landed (``pathway_tpu/device/``), the batcher is
a THIN front-end: it only coalesces; the executor owns dispatch (its
queue, its in-flight budget, its ``backlog.device.*`` attribution), and
the model code inside ``process_batch`` reaches the executor's bucketed
fixed-shape path (``run_batch``).  Two consequences, both deliberate:

* **Pending state is shared across event loops.**  The engine runs each
  epoch's gather under a fresh ``asyncio.run`` loop, and serving threads
  run their own loops; the old per-``id(loop)`` pending dict split one
  logical stream into per-loop fragment batches (and leaked state when a
  loop died before its flusher drained — ``id()`` values recycle).  Now
  one lock-guarded pending list serves every loop, each waiter remembers
  its own loop, and results come home via ``call_soon_threadsafe``.
* **The event loop never blocks on device work.**  Batches run on the
  executor's dispatch thread, so the loop keeps gathering/tokenizing the
  next rows while the device chews the previous batch — the PR 3
  async-committer overlap pattern applied to compute (measured by
  ``benchmarks/device_executor.py``).
"""

from __future__ import annotations

import asyncio
import threading
import time as _time
import weakref
from typing import Any, Callable, Sequence

import numpy as np


def _batch_nbytes(items: list) -> int:
    """Best-effort byte estimate for the executor's in-flight budget."""
    total = 0
    for item in items:
        if isinstance(item, np.ndarray):
            total += item.nbytes
        elif isinstance(item, (bytes, str)):
            total += len(item)
        elif isinstance(item, tuple):
            total += _batch_nbytes(list(item))
    return total


class AsyncMicroBatcher:
    """Coalesces concurrent async submissions into batched process calls.

    ``process_batch(items) -> results`` is the batch callback (typically
    tokenize + ``DeviceExecutor.run_batch``), and it always runs
    off-loop.  ``run_in_thread=False`` (embedders/rerankers: ms-scale
    batches) routes through the executor's dispatch queue — bounded
    budget, ``backlog.device.*`` attribution, ``device_stall``
    injectable.  ``run_in_thread=True`` (LLM generation: seconds-long
    batches) runs each batch on its own thread instead, exactly as
    before — a 5 s generation batch must not head-of-line-block every
    embedder batch behind the single dispatch thread.
    """

    def __init__(
        self,
        process_batch: Callable[[list], Sequence],
        max_batch_size: int = 256,
        flush_delay: float = 0.002,
        run_in_thread: bool = False,
        executor=None,
        name: str | None = None,
    ):
        self.process_batch = process_batch
        self.max_batch_size = max_batch_size
        self.flush_delay = flush_delay
        self.run_in_thread = run_in_thread
        self.name = name or getattr(process_batch, "__name__", "batch")
        self._executor = executor
        # ONE pending list across every event loop (see module docstring);
        # entries are (item, loop, asyncio.Future, Deadline | None,
        # (RequestTrace, enqueued_at) | None) — the deadline is the
        # serving request's ambient budget, checked again at dispatch so
        # an expired waiter never burns device work, and the trace is the
        # request's ambient RequestTrace so a coalesced batch parents each
        # waiter's spans to its OWN trace (engine/tracing.py)
        self._pending: list[tuple[Any, Any, Any, Any, Any]] = []
        self._lock = threading.Lock()
        # loops that currently have a live flusher task.  Keyed by
        # id(loop) but VALIDATED against a weakref to the loop object: a
        # loop closed without cancelling its tasks never runs the
        # flusher's cleanup, and a later loop recycling the same id must
        # not inherit the stale entry (its submissions would never spawn
        # a flusher and could hang).
        self._flushers: dict[int, Any] = {}
        # strong refs: the loop only weak-refs tasks, and a GC'd flusher
        # would strand its pending items
        self._tasks: set = set()

    def _exec(self):
        if self._executor is None:
            from pathway_tpu.device import get_default_executor

            self._executor = get_default_executor()
        return self._executor

    async def submit(self, item: Any) -> Any:
        from pathway_tpu.engine import serving, tracing

        # serving deadline propagation (shed-before-work): an already-
        # expired request never coalesces into a batch at all, and a live
        # deadline rides along to be re-checked at dispatch time
        deadline = serving.current_deadline()
        if deadline is not None and deadline.expired():
            serving.note_deadline_shed("batcher")
            raise serving.DeadlineExceededError(
                "request deadline lapsed before batch coalescing "
                "(shed-before-work)"
            )
        # trace propagation across the thread hop: the ambient trace is
        # captured HERE (the waiter's own context) and rides the entry —
        # the dispatch side may run on any thread/loop
        trace = tracing.current_trace()
        entry_trace = (trace, _time.time()) if trace is not None else None
        loop = asyncio.get_running_loop()
        future = loop.create_future()
        flush_now = False
        spawn_flusher = False
        key = id(loop)
        with self._lock:
            self._pending.append((item, loop, future, deadline, entry_trace))
            if len(self._pending) >= self.max_batch_size:
                flush_now = True
            ref = self._flushers.get(key)
            if ref is None or ref() is not loop:  # absent, dead, or recycled id
                self._flushers[key] = weakref.ref(loop)
                spawn_flusher = True
        if spawn_flusher:
            task = loop.create_task(self._flusher(key))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
        if flush_now:
            self.flush()
        return await future

    def flush(self) -> None:
        """Hand every full (or closing) batch of pending items to the
        executor's dispatch queue.  Callable from any thread."""
        while True:
            with self._lock:
                if not self._pending:
                    return
                batch = self._pending[: self.max_batch_size]
                del self._pending[: self.max_batch_size]
            self._dispatch(batch)

    def _dispatch(self, batch: list[tuple[Any, Any, Any, Any, Any]]) -> None:
        # deadline re-check at the coalesce→dispatch boundary: waiters
        # whose serving deadline lapsed while pending are failed typed
        # here and excluded from the batch — the device never pays for a
        # request the client has already been told is dead
        live = batch
        expired = [
            entry for entry in batch
            if entry[3] is not None and entry[3].expired()
        ]
        if expired:
            from pathway_tpu.engine import serving

            live = [entry for entry in batch if entry not in expired]
            for _item, loop, fut, _ddl, _tr in expired:
                serving.note_deadline_shed("batcher")
                exc = serving.DeadlineExceededError(
                    "request deadline lapsed while coalescing "
                    "(shed-before-work)"
                )
                try:
                    loop.call_soon_threadsafe(_resolve, fut, None, exc)
                except RuntimeError:
                    pass
            if not live:
                return
        # per-waiter coalesce span: one batch, N traces — each waiter's
        # span (its own coalesce wait) parents to its OWN trace
        now = _time.time()
        traces = []
        for _item, _loop, _fut, _ddl, entry_trace in live:
            if entry_trace is not None:
                trace, enqueued_at = entry_trace
                trace.add_span(
                    "serve.batch",
                    enqueued_at,
                    max(0.0, now - enqueued_at),
                    batcher=self.name,
                    batch_size=len(live),
                )
                traces.append(trace)
        items = [entry[0] for entry in live]
        waiters = [(entry[1], entry[2]) for entry in live]

        def job():
            return self.process_batch(items)

        def deliver(device_future) -> None:
            try:
                results = list(device_future.result(timeout=0))
                if len(results) != len(waiters):
                    raise ValueError(
                        f"process_batch returned {len(results)} results "
                        f"for {len(waiters)} items"
                    )
                payload = [(fut, res, None) for (_l, fut), res in zip(waiters, results)]
            except BaseException as exc:  # noqa: BLE001 - delivered to every waiter
                payload = [(fut, None, exc) for (_l, fut) in waiters]
            for (loop, _f), (fut, res, exc) in zip(waiters, payload):
                try:
                    loop.call_soon_threadsafe(_resolve, fut, res, exc)
                except RuntimeError:
                    # the waiter's loop closed before delivery (its epoch
                    # was torn down); nothing is listening anymore
                    pass

        if self.run_in_thread:
            # seconds-long batches (LLM generation) get their own thread:
            # serializing them behind the shared dispatch thread would
            # head-of-line-block every ms-scale embedder batch
            from pathway_tpu.device.executor import _JOB_TRACES, DeviceFuture

            future = DeviceFuture()

            def run_detached():
                # the detached batch thread inherits the waiters' traces
                # the same way a dispatch-thread job does, so run_batch
                # calls inside record attributable device spans
                token = _JOB_TRACES.set(tuple(traces)) if traces else None
                try:
                    future.set_result(job())
                except BaseException as exc:  # noqa: BLE001 - delivered to waiters
                    future.set_exception(exc)
                finally:
                    if token is not None:
                        _JOB_TRACES.reset(token)

            future.add_done_callback(deliver)
            threading.Thread(
                target=run_detached, name=f"batch:{self.name}", daemon=True
            ).start()
            return
        try:
            device_future = self._exec().submit(
                job,
                name=self.name,
                nbytes=_batch_nbytes(items),
                traces=tuple(traces),
            )
        except BaseException as exc:  # noqa: BLE001 - delivered to every waiter
            # submit() itself can fail (ExecutorClosedError after close,
            # a budget timeout) — every coalesced waiter must get the
            # typed error rather than hang on a batch that never queued
            for loop, fut in waiters:
                try:
                    loop.call_soon_threadsafe(_resolve, fut, None, exc)
                except RuntimeError:
                    pass  # that waiter's loop already closed
            return
        device_future.add_done_callback(deliver)

    async def _flusher(self, key: int) -> None:
        # first flush is IMMEDIATE: two zero-sleeps let every already-
        # scheduled same-tick submitter enqueue (the engine gathers an
        # epoch's rows in one tick), then the batch goes — a lone serving
        # query pays no fixed flush_delay latency.  Stragglers that submit
        # after awaiting something else are caught by the flush_delay
        # rounds below.
        loop = asyncio.get_running_loop()
        try:
            await asyncio.sleep(0)
            await asyncio.sleep(0)
            self.flush()
            while True:
                await asyncio.sleep(self.flush_delay)
                with self._lock:
                    if not self._pending:
                        return
                self.flush()
        finally:
            with self._lock:
                # drop only OUR entry — a recycled id may already hold a
                # newer loop's ref (submit validates refs, so a stale
                # entry is harmless, but don't evict a live one)
                ref = self._flushers.get(key)
                if ref is not None and ref() in (loop, None):
                    self._flushers.pop(key, None)


def _resolve(fut, result, exc) -> None:
    if fut.done():
        return
    if exc is not None:
        fut.set_exception(exc)
    else:
        fut.set_result(result)
