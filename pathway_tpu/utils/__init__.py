"""Host-side utilities: micro-batching for the device bridge."""

from pathway_tpu.utils.batching import AsyncMicroBatcher

__all__ = ["AsyncMicroBatcher"]
