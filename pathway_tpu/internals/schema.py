"""Class-based table schemas.

Parity target: ``/root/reference/python/pathway/internals/schema.py`` (955 LoC).
Supports the same user surface: subclassing ``pw.Schema`` with annotations,
``pw.column_definition`` for primary keys / defaults, ``schema_from_types``,
``schema_builder``, ``schema_from_dict``, ``schema_from_csv``, schema algebra
(``|``, ``update_types``, ``without``), and id-type plumbing.
"""

from __future__ import annotations

import csv as _csv
import dataclasses
import typing
from typing import Any, Iterable, Mapping

from pathway_tpu.internals import dtype as dt

_no_default = object()


@dataclasses.dataclass(frozen=True)
class ColumnDefinition:
    primary_key: bool = False
    default_value: Any = _no_default
    dtype: Any = None
    name: str | None = None
    append_only: bool | None = None
    description: str | None = None
    example: Any = _no_default

    @property
    def has_default_value(self) -> bool:
        return self.default_value is not _no_default


def column_definition(
    *,
    primary_key: bool = False,
    default_value: Any = _no_default,
    dtype: Any = None,
    name: str | None = None,
    append_only: bool | None = None,
    description: str | None = None,
    example: Any = _no_default,
) -> ColumnDefinition:
    """Mirrors ``pw.column_definition`` (reference schema.py)."""
    return ColumnDefinition(
        primary_key=primary_key,
        default_value=default_value,
        dtype=dtype,
        name=name,
        append_only=append_only,
        description=description,
        example=example,
    )


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    dtype: dt.DType
    primary_key: bool = False
    default_value: Any = _no_default
    append_only: bool = False
    description: str | None = None

    @property
    def has_default_value(self) -> bool:
        return self.default_value is not _no_default


@dataclasses.dataclass(frozen=True)
class SchemaProperties:
    append_only: bool = False


def is_append_only(schema: "type[Schema]") -> bool:
    """Table-level append-onlyness: the schema-level flag, or every column
    declared append_only — the same fold the reference applies when it
    builds column properties (reference schema.py:251-259)."""
    if schema.__properties__.append_only:
        return True
    cols = schema.__columns__
    return bool(cols) and all(c.append_only for c in cols.values())


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnSchema]
    __properties__: SchemaProperties

    def __init__(cls, name, bases, namespace, append_only: bool | None = None, **kwargs):
        super().__init__(name, bases, namespace, **kwargs)
        columns: dict[str, ColumnSchema] = {}
        for base in bases:
            if hasattr(base, "__columns__"):
                columns.update(base.__columns__)
        hints = namespace.get("__annotations__", {})
        localns = vars(__import__("sys").modules.get(cls.__module__, None) or object())
        for attr, annotation in hints.items():
            if attr.startswith("__"):
                continue
            try:
                if isinstance(annotation, str):
                    annotation = eval(annotation, dict(localns), {})  # noqa: S307
            except Exception:
                annotation = Any
            definition = namespace.get(attr, None)
            if isinstance(definition, ColumnDefinition):
                dtype = dt.wrap(definition.dtype) if definition.dtype is not None else dt.wrap(annotation)
                columns[definition.name or attr] = ColumnSchema(
                    name=definition.name or attr,
                    dtype=dtype,
                    primary_key=definition.primary_key,
                    default_value=definition.default_value,
                    append_only=bool(definition.append_only),
                    description=definition.description,
                )
            else:
                columns[attr] = ColumnSchema(name=attr, dtype=dt.wrap(annotation))
        cls.__columns__ = columns
        cls.__properties__ = SchemaProperties(append_only=bool(append_only))

    # --- introspection (matches reference Schema classmethods) ---
    def column_names(cls) -> list[str]:
        return list(cls.__columns__.keys())

    def columns(cls) -> Mapping[str, ColumnSchema]:
        return dict(cls.__columns__)

    def keys(cls):
        return cls.__columns__.keys()

    def typehints(cls) -> dict[str, Any]:
        return {n: c.dtype.typehint for n, c in cls.__columns__.items()}

    def _dtypes(cls) -> dict[str, dt.DType]:
        return {n: c.dtype for n, c in cls.__columns__.items()}

    def primary_key_columns(cls) -> list[str] | None:
        pkeys = [n for n, c in cls.__columns__.items() if c.primary_key]
        return pkeys or None

    def default_values(cls) -> dict[str, Any]:
        return {n: c.default_value for n, c in cls.__columns__.items() if c.has_default_value}

    def __or__(cls, other: "SchemaMetaclass"):
        cols = dict(cls.__columns__)
        for name, col in other.__columns__.items():
            if name in cols:
                raise ValueError(f"column {name!r} appears in both schemas")
            cols[name] = col
        return schema_from_columns(cols, name=f"{cls.__name__}|{other.__name__}")

    def __getitem__(cls, name: str) -> ColumnSchema:
        return cls.__columns__[name]

    def __repr__(cls) -> str:
        cols = ", ".join(f"{n}: {c.dtype!r}" for n, c in cls.__columns__.items())
        return f"<pw.Schema {cls.__name__}({cols})>"

    def update_types(cls, **kwargs):
        cols = dict(cls.__columns__)
        for name, new_type in kwargs.items():
            if name not in cols:
                raise ValueError(f"no column {name!r} in schema")
            cols[name] = dataclasses.replace(cols[name], dtype=dt.wrap(new_type))
        return schema_from_columns(cols, name=cls.__name__)

    def with_types(cls, **kwargs):
        return cls.update_types(**kwargs)

    def without(cls, *columns):
        names = {c if isinstance(c, str) else c.name for c in columns}
        cols = {n: c for n, c in cls.__columns__.items() if n not in names}
        return schema_from_columns(cols, name=cls.__name__)

    def update_properties(cls, **kwargs):
        new = schema_from_columns(dict(cls.__columns__), name=cls.__name__)
        new.__properties__ = SchemaProperties(**kwargs)
        return new

    def universe_properties(cls):
        return cls.__properties__

    def with_id_type(cls, id_type):
        return cls

    def assert_matches_schema(
        cls,
        other: "SchemaMetaclass",
        *,
        allow_superset: bool = True,
        ignore_primary_keys: bool = True,
    ) -> None:
        for name, col in other.__columns__.items():
            if name not in cls.__columns__:
                raise AssertionError(f"column {name!r} missing")
            mine = cls.__columns__[name]
            if not mine.dtype.is_subclass_of(col.dtype) and col.dtype is not dt.ANY:
                raise AssertionError(
                    f"column {name!r}: {mine.dtype!r} does not match {col.dtype!r}"
                )
        if not allow_superset and set(cls.__columns__) != set(other.__columns__):
            raise AssertionError("schemas have different column sets")


class Schema(metaclass=SchemaMetaclass):
    r"""Base class for user-defined schemas (``class S(pw.Schema): x: int``).

    Example:

    >>> import pathway_tpu as pw
    >>> class Person(pw.Schema):
    ...     name: str
    ...     age: int
    >>> print(Person.column_names())
    ['name', 'age']
    >>> t = pw.debug.table_from_markdown('name | age\nAda | 36', schema=Person)
    >>> pw.debug.compute_and_print(t, include_id=False)
    name | age
    Ada  | 36
    """

    def __init_subclass__(cls, **kwargs):
        # class keywords consumed by SchemaMetaclass.__init__ (e.g.
        # ``class S(pw.Schema, append_only=True)``) must not reach
        # object.__init_subclass__, which takes none
        kwargs.pop("append_only", None)
        super().__init_subclass__(**kwargs)


def schema_from_columns(columns: Mapping[str, ColumnSchema], name: str = "Schema") -> type[Schema]:
    cls = SchemaMetaclass(name, (Schema,), {})
    cls.__columns__ = dict(columns)
    return cls


def schema_from_types(_name: str = "Schema", **kwargs) -> type[Schema]:
    """``pw.schema_from_types(x=int, y=str)``."""
    cols = {n: ColumnSchema(name=n, dtype=dt.wrap(t)) for n, t in kwargs.items()}
    return schema_from_columns(cols, name=_name)


def schema_builder(
    columns: Mapping[str, ColumnDefinition],
    *,
    name: str = "Schema",
    properties: SchemaProperties | None = None,
) -> type[Schema]:
    """``pw.schema_builder`` — build a schema from column definitions."""
    cols = {}
    for attr, definition in columns.items():
        dtype = dt.wrap(definition.dtype) if definition.dtype is not None else dt.ANY
        cname = definition.name or attr
        cols[cname] = ColumnSchema(
            name=cname,
            dtype=dtype,
            primary_key=definition.primary_key,
            default_value=definition.default_value,
            append_only=bool(definition.append_only),
        )
    cls = schema_from_columns(cols, name=name)
    if properties is not None:
        cls.__properties__ = properties
    return cls


def schema_from_dict(
    columns: Mapping[str, Any],
    *,
    name: str = "Schema",
    properties: SchemaProperties | None = None,
) -> type[Schema]:
    """Build a schema from {name: type} or {name: {dtype, primary_key, default_value}}."""
    defs: dict[str, ColumnDefinition] = {}
    for cname, spec in columns.items():
        if isinstance(spec, dict):
            defs[cname] = column_definition(
                dtype=spec.get("dtype"),
                primary_key=spec.get("primary_key", False),
                default_value=spec.get("default_value", _no_default),
            )
        else:
            defs[cname] = column_definition(dtype=spec)
    return schema_builder(defs, name=name, properties=properties)


def _infer_str_type(values: Iterable[str]) -> dt.DType:
    seen = dt.NONE
    for v in values:
        if v == "":
            continue
        for candidate, caster in ((dt.INT, int), (dt.FLOAT, float)):
            try:
                caster(v)
                this = candidate
                break
            except ValueError:
                this = None
        if this is None:
            if v.lower() in ("true", "false"):
                this = dt.BOOL
            else:
                this = dt.STR
        seen = this if seen is dt.NONE else dt.types_lca(seen, this)
    return dt.STR if seen is dt.NONE else seen


def schema_from_csv(
    path: str,
    *,
    name: str = "Schema",
    properties: SchemaProperties | None = None,
    delimiter: str = ",",
    quote: str = '"',
    comment_character: str | None = None,
    escape: str | None = None,
    double_quote_escapes: bool = True,
    num_parsed_rows: int | None = None,
) -> type[Schema]:
    """Infer a schema from a CSV file's header + sampled rows."""
    with open(path, newline="") as f:
        reader = _csv.reader(
            f,
            delimiter=delimiter,
            quotechar=quote,
            escapechar=escape,
            doublequote=double_quote_escapes,
        )
        rows = []
        header: list[str] | None = None
        for row in reader:
            if comment_character and row and row[0].startswith(comment_character):
                continue
            if header is None:
                header = row
                continue
            rows.append(row)
            if num_parsed_rows is not None and len(rows) >= num_parsed_rows:
                break
    if header is None:
        raise ValueError(f"empty CSV file: {path}")
    cols = {}
    for i, cname in enumerate(header):
        values = [r[i] for r in rows if i < len(r)]
        cols[cname] = ColumnSchema(name=cname, dtype=_infer_str_type(values))
    return schema_from_columns(cols, name=name)


def is_subschema(left: type[Schema], right: type[Schema]) -> bool:
    """Reference semantics (internals/schema.py:630): identical column sets
    with every left dtype a subtype of the right one."""
    if left.__columns__.keys() != right.__columns__.keys():
        return False
    for name, col in right.__columns__.items():
        if not left.__columns__[name].dtype.is_subclass_of(col.dtype):
            return False
    return True
