"""Declarative app configuration: YAML → constructed pipeline objects.

Parity target: ``python/pathway/internals/yaml_loader.py`` (the loader
behind template ``app.yaml`` files).  Behavior kept:

* ``!pw.io.csv.read`` / ``!mypkg.mod:factory`` tags import the named
  object (``pw`` → ``pathway_tpu``); a mapping node calls it with the
  mapping as kwargs, an empty scalar calls it with no args (or yields the
  object itself if it is not callable).
* ``$name`` scalars are variables.  A mapping key that is a variable
  defines it for that mapping's subtree (lexical scoping); an ALL_CAPS
  variable with no definition falls back to the environment, its value
  parsed as YAML.
* Each definition is constructed at most once and shared by reference;
  unused definitions raise a warning.
"""

from __future__ import annotations

import builtins
import importlib
import os
import re
import warnings
from typing import Any, Callable

import yaml

_VAR_TAG = "tag:pathway.com,2024:variable"


class Var:
    """A ``$name`` placeholder awaiting resolution."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self) -> str:
        return f"${self.name}"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Var) and other.name == self.name

    def __hash__(self) -> int:
        return hash((Var, self.name))


class Thunk:
    """A tagged node: ``factory(**kwargs)`` deferred until resolution."""

    __slots__ = ("factory", "kwargs", "value", "ready")

    def __init__(self, factory: Callable[..., object] | None, kwargs: dict, *, value: object = None, ready: bool = False):
        self.factory = factory
        self.kwargs = kwargs
        self.value = value
        self.ready = ready


def import_object(path: str) -> object:
    """``pkg.mod:attr.sub`` or dotted-only form; ``pw.`` aliases this package."""
    if path.startswith(("pw.", "pw:")):
        path = "pathway_tpu" + path[2:]
    module_path, colon, attr_path = path.partition(":")
    obj: object
    if colon:
        obj = importlib.import_module(module_path) if module_path else builtins
        attrs = attr_path.split(".") if attr_path else []
    else:
        # dotted form: import the longest importable module prefix, then
        # walk the rest as attributes
        names = module_path.split(".")
        obj = builtins
        attrs = names
        for i in range(len(names), 0, -1):
            prefix = ".".join(names[:i])
            try:
                obj = importlib.import_module(prefix)
                attrs = names[i:]
                break
            except ModuleNotFoundError:
                continue
    for attr in attrs:
        obj = getattr(obj, attr)
    return obj


class _AppLoader(yaml.SafeLoader):
    pass


def _construct_var(loader: _AppLoader, node: yaml.Node) -> Var:
    text = loader.construct_yaml_str(node)
    name = text[1:] if text.startswith("$") else ""
    if not name.isidentifier():
        raise yaml.MarkedYAMLError(
            problem=f"invalid variable name {text!r}",
            problem_mark=node.start_mark,
        )
    return Var(name)


def _construct_tagged(loader: _AppLoader, tag: str, node: yaml.Node) -> Thunk:
    target = import_object(tag)
    if isinstance(node, yaml.MappingNode):
        if not callable(target):
            raise yaml.MarkedYAMLError(
                problem=f"{tag!r} is not callable", problem_mark=node.start_mark
            )
        kwargs = loader.construct_mapping(node, deep=True)
        for key in kwargs:
            if not isinstance(key, (str, Var)):
                raise yaml.MarkedYAMLError(
                    problem=f"expected string key, got {type(key).__name__}",
                    problem_mark=node.start_mark,
                )
        return Thunk(target, kwargs)
    if isinstance(node, yaml.ScalarNode) and node.value == "":
        if callable(target):
            return Thunk(target, {})
        return Thunk(None, {}, value=target, ready=True)
    raise yaml.MarkedYAMLError(
        problem=f"{tag!r} expects a mapping or an empty node",
        problem_mark=node.start_mark,
    )


_AppLoader.add_implicit_resolver(_VAR_TAG, re.compile(r"\$.*"), "$")
_AppLoader.add_constructor(_VAR_TAG, _construct_var)
_AppLoader.add_multi_constructor("!", _construct_tagged)


class _Scope:
    """Lexically scoped variable bindings; tracks which were ever read."""

    def __init__(self, bindings: dict[Var, object], parent: "_Scope | None" = None):
        self.bindings = bindings
        self.parent = parent
        self.used: set[str] = set()
        # resolved terminal values (shared per load): a resolved object that
        # happens to be a Var/dict/list is data now — never re-interpreted
        self.done: dict[int, object] = parent.done if parent is not None else {}

    def warn_unused(self) -> None:
        for var in self.bindings:
            if var.name not in self.used:
                warnings.warn(f"unused YAML variable ${var.name}", stacklevel=3)


_IN_PROGRESS = object()  # cycle guard for definitions being resolved


def _resolve_var(var: Var, scope: _Scope) -> object:
    # lexical scoping: the definition resolves in the scope where it was
    # defined, not at the use site — `$a: $b` at the root must not pick up
    # an inner subtree's $b
    cursor: _Scope | None = scope
    root = scope
    while cursor is not None:
        if var in cursor.bindings:
            cursor.used.add(var.name)
            value = cursor.bindings[var]
            if value is _IN_PROGRESS:
                raise ValueError(f"circular definition of variable ${var.name}")
            cursor.bindings[var] = _IN_PROGRESS
            try:
                resolved = _resolve(value, cursor)
            finally:
                cursor.bindings[var] = value
            cursor.bindings[var] = resolved  # construct once, share
            cursor.done[id(resolved)] = resolved
            return resolved
        root = cursor
        cursor = cursor.parent
    if var.name == var.name.upper():
        raw = os.environ.get(var.name)
        if raw is not None:
            # cache the env definition at the root so every use shares one
            # constructed object (and self-reference is caught, not a hang)
            root.bindings[var] = _IN_PROGRESS
            try:
                resolved = _resolve(yaml.load(raw, _AppLoader), root)
            except BaseException:
                del root.bindings[var]
                raise
            root.bindings[var] = resolved
            root.used.add(var.name)
            root.done[id(resolved)] = resolved
            return resolved
    raise KeyError(f"variable ${var.name} is not defined")


def _split_bindings(mapping: dict) -> tuple[dict[Var, object], dict]:
    bindings = {k: v for k, v in mapping.items() if isinstance(k, Var)}
    rest = {k: v for k, v in mapping.items() if not isinstance(k, Var)}
    return bindings, rest


def _resolve(obj: object, scope: _Scope) -> object:
    if id(obj) in scope.done:
        return obj
    if isinstance(obj, Var):
        return _resolve_var(obj, scope)
    if isinstance(obj, Thunk):
        if not obj.ready:
            # Var keys in a tagged mapping define variables for its kwargs
            bindings, rest = _split_bindings(obj.kwargs)
            inner = _Scope(bindings, parent=scope) if bindings else scope
            kwargs = {k: _resolve(v, inner) for k, v in rest.items()}
            if bindings:
                inner.warn_unused()
            assert obj.factory is not None
            obj.value = obj.factory(**kwargs)
            obj.ready = True  # construct once, share by reference
        return obj.value
    if isinstance(obj, dict):
        bindings, rest = _split_bindings(obj)
        if bindings:
            inner = _Scope(bindings, parent=scope)
            resolved = {k: _resolve(v, inner) for k, v in rest.items()}
            inner.warn_unused()
            return resolved
        return {k: _resolve(v, scope) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_resolve(v, scope) for v in obj]
    return obj


def load_yaml(stream: Any) -> Any:
    """Load an app config: tags construct objects, ``$vars`` resolve."""
    return _resolve(yaml.load(stream, _AppLoader), _Scope({}))


__all__ = ["load_yaml"]
