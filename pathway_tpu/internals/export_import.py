"""Inter-graph table export/import.

Parity target: ``/root/reference/src/engine/dataflow/export.rs:1-205`` and
the Graph-trait surface ``graph.rs:978-984``.  An ``ExportedTable`` is a
thread-safe handle that one graph fills while it runs (rows + a time
frontier) and another graph — typically built after ``G.clear()`` or
running concurrently on another thread — consumes as an input source,
preserving keys, epoch boundaries, and retractions.

The reference wires this through an ``inspect_batch`` on the exporting
dataflow and an ``InputSession`` poller on the importing one; here the
export side is an ``OutputNode`` sink (epoch deltas + ``flush`` frontier
advances) and the import side is a runner poller that stages rows into an
``InputNode`` at their original times (the importing runner folds them
into its own epochs in order, exactly like any other source).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any

from pathway_tpu.engine import dataflow as df
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Table, Universe


class ImportedTableFailed(RuntimeError):
    """The exporting graph failed before finishing (Error::ImportedTableFailed)."""


class ExportedTable:
    """Cross-graph table handle: rows + frontier, filled by the exporter.

    Mirrors export.rs's ExportedTable: ``data_from_offset`` hands out the
    append-only row log incrementally; ``frontier`` is the last closed
    epoch time; ``done``/``failed`` are terminal states.
    """

    def __init__(self, schema: Any):
        self.schema = schema
        self._cond = threading.Condition()
        self._rows: list[tuple[int, tuple, int, int]] = []  # key, row, time, diff
        self._frontier = -1  # static epochs run at time 0, so "nothing closed" is -1
        self._done = False
        self._failed = False

    # -- exporter side ---------------------------------------------------
    def _push(self, key: int, row: tuple, time: int, diff: int) -> None:
        with self._cond:
            self._rows.append((key, row, time, diff))
            self._cond.notify_all()

    def _advance(self, time: int) -> None:
        with self._cond:
            if time > self._frontier:
                self._frontier = time
                self._cond.notify_all()

    def _finish(self, failed: bool = False) -> None:
        with self._cond:
            if not self._done:
                self._done = True
                self._failed = failed
                self._cond.notify_all()

    # -- importer side ---------------------------------------------------
    @property
    def failed(self) -> bool:
        with self._cond:
            return self._failed

    @property
    def done(self) -> bool:
        with self._cond:
            return self._done

    def frontier(self) -> int:
        with self._cond:
            return self._frontier

    def data_from_offset(self, offset: int) -> tuple[list, int]:
        with self._cond:
            return self._rows[offset:], len(self._rows)

    def snapshot(self, offset: int) -> tuple[list, int, int, bool, bool]:
        """(new rows, new offset, frontier, done, failed) — one lock hop."""
        with self._cond:
            return (
                self._rows[offset:],
                len(self._rows),
                self._frontier,
                self._done,
                self._failed,
            )

    def wait(self, offset: int, frontier: int, timeout: float) -> None:
        """Block until new rows/frontier/terminal state appear (or timeout)."""
        with self._cond:
            self._cond.wait_for(
                lambda: len(self._rows) > offset
                or self._frontier > frontier
                or self._done,
                timeout,
            )


class _ExportNode(df.OutputNode):
    """Sink feeding an ExportedTable; aborting runs mark it failed so a
    concurrent importer raises instead of waiting forever (the scopeguard
    in export.rs:143-146)."""

    name = "export"

    def __init__(self, scope, inp, exported: ExportedTable):
        super().__init__(
            scope,
            inp,
            on_data=exported._push,
            on_time_end=exported._advance,
            on_end=exported._finish,
        )
        self._exported = exported

    def on_abort(self):
        self._exported._finish(failed=True)


def export_table(table: Table) -> ExportedTable:
    r"""Register ``table`` for export from the CURRENT graph's next run.

    The handle fills while ``pw.run()`` executes and is complete once the
    run finishes; pass it to :func:`import_table` inside another graph
    (sequentially after ``G.clear()``, or on a concurrent run).
    Match: ``graph.rs:978`` ``export_table``.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('a | b\n1 | 2\n3 | 4')
    >>> exported = pw.export_table(t.select(s=pw.this.a + pw.this.b))
    >>> _ = pw.run()
    >>> exported.done
    True
    >>> pw.G.clear()  # a NEW graph imports the finished handle
    >>> imported = pw.import_table(exported)
    >>> pw.debug.compute_and_print(imported, include_id=False)
    s
    3
    7
    """
    exported = ExportedTable(table.schema)

    def attach(lowerer, node):
        return _ExportNode(lowerer.scope, node, exported)

    G.add_sink("export", table, attach)
    return exported


class _ImportPoller:
    """Runner poller draining an ExportedTable into an InputNode.

    Rows keep their original keys and times; the importing runner merges
    them into its own epoch sequence in order (InputNode staging), so
    epoch boundaries survive the hop exactly like the reference's
    ``input_session.update_at(key, time, diff)`` (export.rs:169-199).
    """

    def __init__(self, node: df.InputNode, exported: ExportedTable):
        self.node = node
        self.exported = exported
        self._offset = 0
        self._held: deque = deque()  # rows of epochs the exporter hasn't closed
        self.finished = False

    def poll(self) -> bool:
        if self.finished:
            return True
        rows, self._offset, frontier, done, failed = self.exported.snapshot(
            self._offset
        )
        if failed:
            raise ImportedTableFailed(
                "imported table's source graph failed before finishing"
            )
        # only stage rows from CLOSED exporter epochs (time <= frontier):
        # the importing runner treats any staged time as a complete epoch,
        # so releasing a half-pushed epoch would expose a partial state the
        # exporting graph never had
        self._held.extend(rows)
        while self._held and (done or self._held[0][2] <= frontier):
            key, row, time, diff = self._held.popleft()
            self.node.insert(key, row, time, diff)
        if done:
            self.node.close()
            self.finished = True
            return True
        return False


def import_table(exported: ExportedTable) -> Table:
    """A Table in the CURRENT graph backed by an :class:`ExportedTable`
    produced by another graph.  Match: ``graph.rs:984`` ``import_table``.
    """

    def build(lowerer) -> df.Node:
        node = df.InputNode(lowerer.scope)
        lowerer.pollers.append(_ImportPoller(node, exported))
        return node

    return Table(exported.schema, build, universe=Universe())
