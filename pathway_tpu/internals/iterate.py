"""``pw.iterate`` — fixed-point computation.

Parity target: ``parse_graph.py:157-181`` (IterateOperator) +
``dataflow.rs:4185-4724``.  The body function receives proxy tables bound to
a nested engine scope; tables returned under the same keyword are fed back
until quiescence (semi-naive, per outer epoch), as in differential's
iterative scopes.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import dataflow as df
from pathway_tpu.internals import parse_graph
from pathway_tpu.internals.table import Lowerer, Table, Universe


class _IterationProxyTable(Table):
    """Table bound to an iteration input inside the nested scope."""

    def __init__(self, schema, node_getter):
        super().__init__(schema, build=lambda lowerer: node_getter(lowerer), universe=Universe())


class _IterSubLowerer(Lowerer):
    """Lowerer for the iteration subscope.

    Tables created by the body build in the subscope; any other table is an
    outer-scope collection — it lowers in the OUTER scope and streams into
    the subscope through an import InputNode (the reference's scope
    import/export, dataflow.rs:4315-4724).
    """

    def __init__(self, subscope, outer_lowerer, marker: int, import_pairs: list):
        super().__init__(subscope)
        self._outer = outer_lowerer
        self._marker = marker  # G.tables index where the body started
        self._scan = marker
        self._inside_ids: set[int] = set()
        self._imports = import_pairs

    def _is_inside(self, table) -> bool:
        tables = parse_graph.G.tables
        while self._scan < len(tables):
            self._inside_ids.add(id(tables[self._scan]))
            self._scan += 1
        return id(table) in self._inside_ids

    def node(self, table) -> df.Node:
        key = id(table)
        if key in self.memo:
            return self.memo[key]
        if self._is_inside(table):
            self.memo[key] = table._build(self)
            return self.memo[key]
        outer_node = self._outer.node(table)
        sub_in = df.InputNode(self.scope)
        self._imports.append((outer_node, sub_in))
        self.memo[key] = sub_in
        return sub_in


def iterate(func: Callable, iteration_limit: int | None = None, **kwargs: Table):
    r"""Iterate ``func`` to fixed point.

    ``kwargs`` are input tables; ``func(**tables)`` returns a dict (or
    dataclass/namedtuple) of tables.  Returned keys matching input names are
    fed back for the next round; the fixed point of each returned table is
    the result.

    Example:

    >>> import pathway_tpu as pw
    >>> def collatz(t):
    ...     return t.select(
    ...         v=pw.if_else(
    ...             pw.this.v == 1, 1,
    ...             pw.if_else(pw.this.v % 2 == 0, pw.this.v // 2, 3 * pw.this.v + 1),
    ...         )
    ...     )
    >>> t = pw.debug.table_from_markdown('v\n6\n27')
    >>> res = pw.iterate(collatz, t=t)
    >>> pw.debug.compute_and_print(res, include_id=False)
    v
    1
    1
    """
    input_names = list(kwargs.keys())
    input_tables = [kwargs[n] for n in input_names]

    # results are produced lazily: a recipe that builds the IterateNode once
    holder: dict[str, Any] = {}

    def ensure_built(lowerer: Lowerer) -> dict[str, df.Node]:
        cache_key = id(lowerer)
        if holder.get("lowerer_id") == cache_key:
            return holder["result_nodes_by_name"]

        outer_nodes = [lowerer.node(t) for t in input_tables]
        result_order: list[str] = []

        def build_body(subscope: df.Scope, iter_inputs: list[df.InputNode]):
            import_pairs: list = []
            marker = len(parse_graph.G.tables)
            sub_lowerer = _IterSubLowerer(subscope, lowerer, marker, import_pairs)
            proxies = {}
            for name, table, iin in zip(input_names, input_tables, iter_inputs):
                proxy = _IterationProxyTable(table.schema, lambda lw, _iin=iin: _iin)
                sub_lowerer.memo[id(proxy)] = iin
                proxies[name] = proxy
            returned = func(**proxies)
            if isinstance(returned, Table):
                returned = {input_names[0]: returned}
            elif not isinstance(returned, dict):
                # dataclass / namedtuple
                if hasattr(returned, "_asdict"):
                    returned = returned._asdict()
                else:
                    returned = {
                        k: v for k, v in vars(returned).items() if isinstance(v, Table)
                    }
            result_order.extend(returned.keys())
            holder["returned_schemas"] = {k: v.schema for k, v in returned.items()}
            result_nodes = [sub_lowerer.node(t) for t in returned.values()]
            back_pairs = []
            for n in input_names:
                if n in returned:
                    back_pairs.append((input_names.index(n), sub_lowerer.node(returned[n])))
            return result_nodes, back_pairs, import_pairs

        node = df.IterateNode(
            lowerer.scope, outer_nodes, build_body, limit=iteration_limit
        )

        result_nodes_by_name = {}
        for i, name in enumerate(result_order):
            result_nodes_by_name[name] = df.IterateResultNode(lowerer.scope, node, i)
        holder["lowerer_id"] = cache_key
        holder["result_nodes_by_name"] = result_nodes_by_name
        return result_nodes_by_name

    # trial build to learn the returned table names/schemas (pure, on a
    # throwaway scope)
    trial_lowerer = Lowerer(df.Scope())
    trial_nodes = ensure_built(trial_lowerer)
    schemas = holder["returned_schemas"]

    results = {}
    for name in trial_nodes:
        def make_build(n=name):
            def build(lowerer: Lowerer) -> df.Node:
                return ensure_built(lowerer)[n]

            return build

        results[name] = Table(schemas[name], make_build(), universe=Universe())
    holder["lowerer_id"] = None  # invalidate trial

    if len(results) == 1:
        return next(iter(results.values()))
    import types

    return types.SimpleNamespace(**results)


def iterate_universe(func: Callable, **kwargs):
    return iterate(func, **kwargs)
