"""Error-log tables (parity: dataflow.rs:582-673, pw.global_error_log).

With ``terminate_on_error=False`` the engine routes row-level failures into
an error log instead of raising; ``Value::Error`` poisons dependent cells
and ``remove_errors`` filters poisoned rows (same model as the reference).
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import dataflow as df
from pathway_tpu.engine.types import sequential_key
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Lowerer, Table, Universe

_ERROR_LOG_SCHEMA = schema_mod.schema_from_columns(
    {
        "operator_id": schema_mod.ColumnSchema(name="operator_id", dtype=dt.INT),
        "message": schema_mod.ColumnSchema(name="message", dtype=dt.STR),
    }
)


class _ErrorLogNode(df.InputNode):
    """Fed by the scope's error channel at epoch boundaries."""

    name = "error_log"

    def __init__(self, scope: df.Scope):
        super().__init__(scope)
        self.finished = True
        self._drained = 0

    def _drain(self, time):
        log = self.scope.error_log
        out = []
        for node, key, message in log[self._drained :]:
            k = sequential_key(self._drained)
            out.append((k, (node.id if node is not None else -1, message), 1))
            self._drained += 1
        self.send(out, time)

    def step(self, time):
        pass

    def flush(self, time):
        # errors surface at the epoch BOUNDARY: draining in step() would
        # miss failures from nodes that run later in the same epoch (the
        # downstream delivery then happens in the finish quiesce)
        self._drain(time)

    def on_finish(self):
        self._drain(self.scope.current_time)


_global_log_table: Table | None = None


def global_error_log() -> Table:
    global _global_log_table
    if _global_log_table is None:

        def build(lowerer: Lowerer) -> df.Node:
            return _ErrorLogNode(lowerer.scope)

        _global_log_table = Table(_ERROR_LOG_SCHEMA, build, universe=Universe())
    return _global_log_table


class local_error_log:
    """Context manager scoping an error log (parity: pw.local_error_log)."""

    def __enter__(self) -> Table:
        def build(lowerer: Lowerer) -> df.Node:
            return _ErrorLogNode(lowerer.scope)

        self._table = Table(_ERROR_LOG_SCHEMA, build, universe=Universe())
        return self._table

    def __exit__(self, *exc) -> None:
        return None
