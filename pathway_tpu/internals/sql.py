"""``pw.sql`` — a limited SQL → Table-operations compiler.

Parity target: ``/root/reference/python/pathway/internals/sql.py`` (726 LoC,
sqlglot-based).  sqlglot is not available in this environment, so this is a
self-contained compiler for the subset the reference documents: SELECT
projections/expressions with aliases, WHERE, GROUP BY (+ aggregates
COUNT/SUM/AVG/MIN/MAX), HAVING, UNION ALL, and dotted table references over
the keyword-provided tables.
"""

from __future__ import annotations

import ast
import re
from typing import Any

from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this

_AGGS = {
    "count": reducers.count,
    "sum": reducers.sum,
    "avg": reducers.avg,
    "min": reducers.min,
    "max": reducers.max,
}


def _sql_to_python(expr: str) -> str:
    s = expr
    s = re.sub(r"(?<![<>!=])=(?!=)", "==", s)
    s = re.sub(r"<>", "!=", s)
    s = re.sub(r"\bAND\b", "&", s, flags=re.I)
    s = re.sub(r"\bOR\b", "|", s, flags=re.I)
    s = re.sub(r"\bNOT\b", "~", s, flags=re.I)
    s = re.sub(r"\bIS\s+NOT\s+NULL\b", ".is_not_none()", s, flags=re.I)
    s = re.sub(r"\bIS\s+NULL\b", ".is_none()", s, flags=re.I)
    s = s.replace("'", '"')
    return s


class _ExprBuilder(ast.NodeTransformer):
    def __init__(self, tables: dict[str, Table], in_group: bool):
        self.tables = tables
        self.in_group = in_group
        self.aggregates_used = False


def _compile_expr(sql_expr: str, tables: dict[str, Table], group_ctx: bool = False):
    py = _sql_to_python(sql_expr)
    tree = ast.parse(py, mode="eval")

    def build(node) -> Any:
        if isinstance(node, ast.Expression):
            return build(node.body)
        if isinstance(node, ast.BinOp):
            op_map = {
                ast.Add: "__add__",
                ast.Sub: "__sub__",
                ast.Mult: "__mul__",
                ast.Div: "__truediv__",
                ast.FloorDiv: "__floordiv__",
                ast.Mod: "__mod__",
                ast.Pow: "__pow__",
                ast.BitAnd: "__and__",
                ast.BitOr: "__or__",
                ast.BitXor: "__xor__",
            }
            left = build(node.left)
            right = build(node.right)
            return getattr(ColumnExpression, op_map[type(node.op)])(
                left if isinstance(left, ColumnExpression) else _const(left),
                right,
            )
        if isinstance(node, ast.UnaryOp):
            v = build(node.operand)
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.Invert):
                return ~v
            return v
        if isinstance(node, ast.Compare):
            left = build(node.left)
            right = build(node.comparators[0])
            op = node.ops[0]
            le = left if isinstance(left, ColumnExpression) else _const(left)
            if isinstance(op, ast.Eq):
                return le == right
            if isinstance(op, ast.NotEq):
                return le != right
            if isinstance(op, ast.Lt):
                return le < right
            if isinstance(op, ast.LtE):
                return le <= right
            if isinstance(op, ast.Gt):
                return le > right
            if isinstance(op, ast.GtE):
                return le >= right
            raise ValueError("unsupported comparison")
        if isinstance(node, ast.Name):
            return getattr(this, node.id)
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id in self_tables:
                return getattr(self_tables[base.id], node.attr)
            inner = build(base)
            return getattr(inner, node.attr)
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Call):
            fname = node.func.id.lower() if isinstance(node.func, ast.Name) else None
            if fname in _AGGS:
                args = [build(a) for a in node.args]
                if fname == "count":
                    return reducers.count()
                return _AGGS[fname](*args)
            if isinstance(node.func, ast.Attribute):
                # method call like x.is_none()
                inner = build(node.func.value)
                return getattr(inner, node.func.attr)(*[build(a) for a in node.args])
            raise ValueError(f"unsupported SQL function {fname}")
        if isinstance(node, ast.Starred) and isinstance(node.value, ast.Name):
            return node.value.id
        raise ValueError(f"unsupported SQL expression node {ast.dump(node)}")

    self_tables = tables
    return build(tree)


def _const(v):
    from pathway_tpu.internals.expression import ColumnConstExpression

    return ColumnConstExpression(v)


def _split_top(s: str, sep: str = ",") -> list[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur).strip())
    return parts


def sql(query: str, **tables: Table) -> Table:
    """Execute a SQL query over the provided tables."""
    q = query.strip().rstrip(";")
    if re.search(r"\bUNION\s+ALL\b", q, flags=re.I):
        parts = re.split(r"\bUNION\s+ALL\b", q, flags=re.I)
        result = sql(parts[0], **tables)
        for p in parts[1:]:
            result = result.concat_reindex(sql(p, **tables))
        return result

    m = re.match(
        r"SELECT\s+(?P<proj>.+?)\s+FROM\s+(?P<frm>[\w.]+)"
        r"(?:\s+WHERE\s+(?P<where>.+?))?"
        r"(?:\s+GROUP\s+BY\s+(?P<group>.+?))?"
        r"(?:\s+HAVING\s+(?P<having>.+?))?$",
        q,
        flags=re.I | re.S,
    )
    if not m:
        raise ValueError(f"unsupported SQL: {query!r}")
    table_name = m.group("frm")
    if table_name not in tables:
        raise ValueError(f"unknown table {table_name!r}")
    t = tables[table_name]

    if m.group("where"):
        t = t.filter(_compile_expr(m.group("where"), tables))

    proj_parts = _split_top(m.group("proj"))
    group = m.group("group")
    select_exprs: dict[str, Any] = {}
    auto = 0
    for part in proj_parts:
        am = re.match(r"(.+?)\s+AS\s+(\w+)$", part, flags=re.I)
        if am:
            raw, alias = am.group(1), am.group(2)
        else:
            raw, alias = part, None
        if raw.strip() == "*":
            for n in t.column_names():
                select_exprs[n] = getattr(this, n)
            continue
        e = _compile_expr(raw, tables, group_ctx=group is not None)
        if alias is None:
            alias = raw.strip() if re.match(r"^\w+$", raw.strip()) else f"col_{auto}"
            auto += 1
        select_exprs[alias] = e

    if group:
        gcols = [g.strip() for g in _split_top(group)]
        grefs = [getattr(this, g) for g in gcols]
        result = t.groupby(*grefs).reduce(**select_exprs)
        if m.group("having"):
            result = result.filter(_compile_expr(m.group("having"), tables, group_ctx=True))
        return result
    return t.select(**select_exprs)
