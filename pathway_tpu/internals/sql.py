"""``pw.sql`` — a SQL → Table-operations compiler.

Parity target: ``/root/reference/python/pathway/internals/sql.py`` (726 LoC,
sqlglot-based).  sqlglot is not available in this environment, so this is a
self-contained tokenizer + recursive-descent parser covering the subset the
reference documents:

* SELECT projections (``*``, ``tbl.*``, expressions, aliases), DISTINCT
* FROM with multiple tables / aliases, comma cross-joins, and
  INNER/LEFT/RIGHT/FULL OUTER JOIN ... ON with equality conditions
  (extra non-equi ON terms become post-filters on inner joins)
* WHERE with AND/OR/NOT, comparisons, BETWEEN, IN (literal list),
  IS [NOT] NULL
* GROUP BY (columns or expressions) with aggregates COUNT(*)/COUNT(x)/
  SUM/AVG/MIN/MAX, and HAVING (aggregates allowed)
* subqueries in FROM: ``SELECT ... FROM (SELECT ...) alias``
* UNION ALL (concatenation) and UNION (deduplicating), INTERSECT and
  EXCEPT (distinct set semantics, value-based, INTERSECT binding tighter
  as in standard SQL)
* WITH (non-recursive CTEs, referencable by later CTEs and the body)
* uncorrelated scalar subqueries in WHERE/HAVING
  (``WHERE v > (SELECT AVG(v) FROM t)`` — must be a single-row aggregate)
* projection-alias reuse in HAVING (``SELECT SUM(v) AS s ... HAVING s > 3``)
* CASE (searched and simple forms, aggregates allowed in branches under
  GROUP BY), IF(cond, a, b), NULLIF(a, b), COALESCE

Not covered (as in the reference's documented limitations): correlated
subqueries, window functions, ORDER BY/LIMIT (meaningless on streams).
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference, coalesce
from pathway_tpu.internals.table import JoinMode, JoinResult, Table
from pathway_tpu.internals.thisclass import left as left_ph, right as right_ph, this

_AGG_NAMES = {"count", "sum", "avg", "min", "max"}
_AGGS = {
    "count": reducers.count,
    "sum": reducers.sum,
    "avg": reducers.avg,
    "min": reducers.min,
    "max": reducers.max,
}

_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "union",
    "all", "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "as", "and", "or", "not", "is", "null", "between", "in", "true", "false",
    "with", "recursive", "intersect", "except", "case", "when", "then",
    "else", "end",
}


class SqlError(ValueError):
    pass


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d*|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><>|!=|<=|>=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.)
    """,
    re.VERBOSE,
)


def _tokenize(q: str) -> list[tuple[str, Any]]:
    out: list[tuple[str, Any]] = []
    pos = 0
    while pos < len(q):
        m = _TOKEN_RE.match(q, pos)
        if not m:
            raise SqlError(f"cannot tokenize SQL near {q[pos:pos+20]!r}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        text = m.group()
        if m.lastgroup == "num":
            out.append(("num", float(text) if "." in text else int(text)))
        elif m.lastgroup == "str":
            out.append(("str", text[1:-1].replace("''", "'")))
        elif m.lastgroup == "name":
            low = text.lower()
            if low in _KEYWORDS:
                out.append(("kw", low))
            else:
                out.append(("name", text))
        else:
            out.append(("op", text))
    out.append(("end", None))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, Any]]):
        self.toks = tokens
        self.i = 0

    def peek(self, k: int = 0) -> tuple[str, Any]:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> tuple[str, Any]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> str | None:
        t, v = self.peek()
        if t == "kw" and v in kws:
            self.next()
            return v
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlError(f"expected {kw.upper()!r}, got {self.peek()!r}")

    def accept_op(self, *ops: str) -> str | None:
        t, v = self.peek()
        if t == "op" and v in ops:
            self.next()
            return v
        return None

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlError(f"expected {op!r}, got {self.peek()!r}")

    def expect_name(self) -> str:
        t, v = self.next()
        if t != "name":
            raise SqlError(f"expected identifier, got {(t, v)!r}")
        return v


# ---------------------------------------------------------------------------
# AST (plain tuples keep the parser small)
#   ("col", qualifier|None, name) ("const", v) ("bin", op, l, r)
#   ("and", l, r) ("or", l, r) ("not", e) ("isnull", e, negate)
#   ("agg", fname, arg|None) ("func", fname, args) ("star", qualifier|None)
# ---------------------------------------------------------------------------


def _parse_expr(p: _Parser):
    return _parse_or(p)


def _parse_or(p: _Parser):
    e = _parse_and(p)
    while p.accept_kw("or"):
        e = ("or", e, _parse_and(p))
    return e


def _parse_and(p: _Parser):
    e = _parse_not(p)
    while p.accept_kw("and"):
        e = ("and", e, _parse_not(p))
    return e


def _parse_not(p: _Parser):
    if p.accept_kw("not"):
        return ("not", _parse_not(p))
    return _parse_cmp(p)


def _parse_cmp(p: _Parser):
    e = _parse_add(p)
    op = p.accept_op("=", "!=", "<>", "<", "<=", ">", ">=")
    if op:
        r = _parse_add(p)
        return ("bin", {"<>": "!=", "=": "=="}.get(op, op), e, r)
    if p.accept_kw("is"):
        negate = bool(p.accept_kw("not"))
        p.expect_kw("null")
        return ("isnull", e, negate)
    if p.accept_kw("between"):
        lo = _parse_add(p)
        p.expect_kw("and")
        hi = _parse_add(p)
        return ("and", ("bin", ">=", e, lo), ("bin", "<=", e, hi))
    if p.accept_kw("not"):
        p.expect_kw("in")
        return ("not", _parse_in_tail(p, e))
    if p.accept_kw("in"):
        return _parse_in_tail(p, e)
    return e


def _parse_in_tail(p: _Parser, e):
    p.expect_op("(")
    if p.peek() in (("kw", "select"), ("kw", "with")):
        raise SqlError(
            "IN (SELECT ...) subqueries are not supported; rewrite as a JOIN"
        )
    items = [_parse_add(p)]
    while p.accept_op(","):
        items.append(_parse_add(p))
    p.expect_op(")")
    out = ("bin", "==", e, items[0])
    for it in items[1:]:
        out = ("or", out, ("bin", "==", e, it))
    return out


def _parse_add(p: _Parser):
    e = _parse_mul(p)
    while True:
        op = p.accept_op("+", "-")
        if not op:
            return e
        e = ("bin", op, e, _parse_mul(p))


def _parse_mul(p: _Parser):
    e = _parse_unary(p)
    while True:
        op = p.accept_op("*", "/", "%")
        if not op:
            return e
        e = ("bin", op, e, _parse_unary(p))


def _parse_unary(p: _Parser):
    if p.accept_op("-"):
        return ("bin", "-", ("const", 0), _parse_unary(p))
    return _parse_primary(p)


def _parse_primary(p: _Parser):
    t, v = p.peek()
    if t == "num" or t == "str":
        p.next()
        return ("const", v)
    if t == "kw" and v in ("true", "false"):
        p.next()
        return ("const", v == "true")
    if t == "kw" and v == "null":
        p.next()
        return ("const", None)
    if t == "kw" and v == "case":
        # CASE [operand] WHEN x THEN y [WHEN ...] [ELSE z] END
        # (searched and simple forms — sqlglot's Case node in the
        # reference maps to the same if_else chain, sql.py:69)
        p.next()
        operand = None
        if p.peek() != ("kw", "when"):
            operand = _parse_expr(p)
        whens = []
        while p.accept_kw("when"):
            cond = _parse_expr(p)
            p.expect_kw("then")
            whens.append((cond, _parse_expr(p)))
        default = ("const", None)
        if p.accept_kw("else"):
            default = _parse_expr(p)
        p.expect_kw("end")
        if not whens:
            raise SqlError("CASE requires at least one WHEN clause")
        # operand stays a single AST node: the simple form compiles it ONCE
        # and shares the compiled expression across every WHEN comparison
        return ("case", operand, whens, default)
    if t == "op" and v == "(":
        p.next()
        if p.peek() in (("kw", "select"), ("kw", "with")):
            sub = _parse_query(p)
            p.expect_op(")")
            return ("scalar_subquery", sub)
        e = _parse_expr(p)
        p.expect_op(")")
        return e
    if t == "op" and v == "*":
        p.next()
        return ("star", None)
    if t == "name":
        name = p.expect_name()
        if p.peek() == ("op", "("):
            p.next()
            fname = name.lower()
            if p.accept_op(")"):
                args = []
            else:
                if fname == "count" and p.peek() == ("op", "*"):
                    p.next()
                    p.expect_op(")")
                    return ("agg", "count", None)
                args = [_parse_expr(p)]
                while p.accept_op(","):
                    args.append(_parse_expr(p))
                p.expect_op(")")
            if fname in _AGG_NAMES:
                return ("agg", fname, args[0] if args else None)
            return ("func", fname, args)
        if p.peek() == ("op", "."):
            p.next()
            if p.peek() == ("op", "*"):
                p.next()
                return ("star", name)
            col = p.expect_name()
            return ("col", name, col)
        return ("col", None, name)
    raise SqlError(f"unexpected token {(t, v)!r}")


# ---------------------------------------------------------------------------
# SELECT statement structure
# ---------------------------------------------------------------------------


def _parse_select(p: _Parser) -> dict:
    p.expect_kw("select")
    distinct = bool(p.accept_kw("distinct"))
    projections = []  # (ast | ("star", qual), alias | None)
    while True:
        e = _parse_expr(p)
        alias = None
        if p.accept_kw("as"):
            alias = p.expect_name()
        elif p.peek()[0] == "name":
            alias = p.expect_name()
        projections.append((e, alias))
        if not p.accept_op(","):
            break
    p.expect_kw("from")
    from_items = [_parse_from_item(p)]
    joins = []  # (mode, item, on_ast | None)
    while True:
        if p.accept_op(","):
            joins.append(("cross", _parse_from_item(p), None))
            continue
        mode = None
        if p.accept_kw("cross"):
            p.expect_kw("join")
            joins.append(("cross", _parse_from_item(p), None))
            continue
        if p.accept_kw("inner"):
            mode = "inner"
        elif p.accept_kw("left"):
            p.accept_kw("outer")
            mode = "left"
        elif p.accept_kw("right"):
            p.accept_kw("outer")
            mode = "right"
        elif p.accept_kw("full"):
            p.accept_kw("outer")
            mode = "outer"
        if mode is None and not (p.peek() == ("kw", "join")):
            break
        p.expect_kw("join")
        item = _parse_from_item(p)
        p.expect_kw("on")
        on = _parse_expr(p)
        joins.append((mode or "inner", item, on))
    where = group = having = None
    if p.accept_kw("where"):
        where = _parse_expr(p)
    if p.accept_kw("group"):
        p.expect_kw("by")
        group = [_parse_expr(p)]
        while p.accept_op(","):
            group.append(_parse_expr(p))
    if p.accept_kw("having"):
        having = _parse_expr(p)
    return dict(
        distinct=distinct,
        projections=projections,
        from_items=from_items,
        joins=joins,
        where=where,
        group=group,
        having=having,
    )


def _parse_from_item(p: _Parser):
    if p.peek() == ("op", "("):
        p.next()
        sub = _parse_query(p)
        p.expect_op(")")
        p.accept_kw("as")
        alias = p.expect_name()
        return ("subquery", sub, alias)
    name = p.expect_name()
    alias = None
    if p.accept_kw("as"):
        alias = p.expect_name()
    elif p.peek()[0] == "name":
        alias = p.expect_name()
    return ("table", name, alias or name)


def _parse_query(p: _Parser):
    ctes = []
    if p.accept_kw("with"):
        if p.accept_kw("recursive"):
            raise SqlError("WITH RECURSIVE is not supported; use pw.iterate")
        while True:
            name = p.expect_name()
            p.expect_kw("as")
            p.expect_op("(")
            sub = _parse_query(p)
            p.expect_op(")")
            ctes.append((name, sub))
            if not p.accept_op(","):
                break
    stmts = [_parse_select(p)]
    ops = []  # ("union"|"intersect"|"except", "all"|"distinct")
    while True:
        if p.accept_kw("union"):
            ops.append(("union", "all" if p.accept_kw("all") else "distinct"))
        elif p.accept_kw("intersect"):
            if p.accept_kw("all"):
                raise SqlError("INTERSECT ALL is not supported")
            ops.append(("intersect", "distinct"))
        elif p.accept_kw("except"):
            if p.accept_kw("all"):
                raise SqlError("EXCEPT ALL is not supported")
            ops.append(("except", "distinct"))
        else:
            break
        stmts.append(_parse_select(p))
    body = ("compound", stmts, ops) if ops else ("select", stmts[0])
    return ("with", ctes, body) if ctes else body


# ---------------------------------------------------------------------------
# compilation
# ---------------------------------------------------------------------------


class _Env:
    """Name resolution over a working table with mangled column names."""

    def __init__(self, table: Table, qualified: dict[tuple[str, str], str]):
        # qualified: (alias, col) -> mangled column name in `table`
        self.table = table
        self.qualified = qualified

    def resolve(self, qualifier: str | None, name: str) -> ColumnExpression:
        if qualifier is not None:
            key = (qualifier, name)
            if key not in self.qualified:
                raise SqlError(f"unknown column {qualifier}.{name}")
            return ColumnReference(this, self.qualified[key])
        hits = [m for (al, col), m in self.qualified.items() if col == name]
        if not hits:
            raise SqlError(f"unknown column {name!r}")
        if len(set(hits)) > 1:
            raise SqlError(f"ambiguous column {name!r}; qualify it")
        return ColumnReference(this, hits[0])

    def all_columns(self, qualifier: str | None) -> list[tuple[str, str]]:
        """[(output name, mangled name)] for SELECT * / alias.*"""
        out = []
        seen = set()
        for (al, col), m in self.qualified.items():
            if al.startswith("#"):
                continue  # hidden scalar-subquery bindings
            if qualifier is not None and al != qualifier:
                continue
            if col in seen:
                raise SqlError(
                    f"SELECT {'*' if qualifier is None else qualifier + '.*'}: "
                    f"duplicate column name {col!r}; project explicitly"
                )
            seen.add(col)
            out.append((col, m))
        return out


def _compile_scalar(ast, env: _Env, agg_ok: bool = False) -> Any:
    kind = ast[0]
    if kind == "const":
        return expr_mod.ColumnConstExpression(ast[1])
    if kind == "col":
        return env.resolve(ast[1], ast[2])
    if kind == "bin":
        op, l_ast, r_ast = ast[1], ast[2], ast[3]
        le = _compile_scalar(l_ast, env, agg_ok)
        re_ = _compile_scalar(r_ast, env, agg_ok)
        return expr_mod.ColumnBinaryOpExpression(op, le, re_)
    if kind == "and":
        return expr_mod.ColumnBinaryOpExpression(
            "&", _compile_scalar(ast[1], env, agg_ok), _compile_scalar(ast[2], env, agg_ok)
        )
    if kind == "or":
        return expr_mod.ColumnBinaryOpExpression(
            "|", _compile_scalar(ast[1], env, agg_ok), _compile_scalar(ast[2], env, agg_ok)
        )
    if kind == "not":
        return ~_compile_scalar(ast[1], env, agg_ok)
    if kind == "isnull":
        e = _compile_scalar(ast[1], env, agg_ok)
        return e.is_not_none() if ast[2] else e.is_none()
    if kind == "agg":
        if not agg_ok:
            raise SqlError("aggregate outside GROUP BY context")
        fname, arg = ast[1], ast[2]
        if fname == "count" and arg is None:
            return reducers.count()
        return _AGGS[fname](_compile_scalar(arg, env, agg_ok))
    if kind == "case":
        operand, whens, default = ast[1], ast[2], ast[3]
        op_expr = (
            _compile_scalar(operand, env, agg_ok) if operand is not None else None
        )
        out = _compile_scalar(default, env, agg_ok)
        for cond_ast, then_ast in reversed(whens):
            cond = _compile_scalar(cond_ast, env, agg_ok)
            if op_expr is not None:
                cond = expr_mod.ColumnBinaryOpExpression("==", op_expr, cond)
            out = expr_mod.IfElseExpression(
                cond, _compile_scalar(then_ast, env, agg_ok), out
            )
        return out
    if kind == "func":
        fname, args = ast[1], ast[2]
        compiled = [_compile_scalar(a, env, agg_ok) for a in args]
        if fname == "coalesce":
            return coalesce(*compiled)
        if fname in ("if", "iff"):
            if len(compiled) != 3:
                raise SqlError(
                    f"IF takes 3 arguments (condition, then, else); got {len(compiled)}"
                )
            return expr_mod.IfElseExpression(*compiled)
        if fname == "nullif":
            if len(compiled) != 2:
                raise SqlError(f"NULLIF takes 2 arguments; got {len(compiled)}")
            return expr_mod.IfElseExpression(
                expr_mod.ColumnBinaryOpExpression("==", compiled[0], compiled[1]),
                expr_mod.ColumnConstExpression(None),
                compiled[0],
            )
        raise SqlError(f"unsupported SQL function {fname!r}")
    if kind == "anycol":
        # a scalar-subquery placeholder inside HAVING: constant per group,
        # so ANY over the group extracts it through the reduce
        return reducers.any(env.resolve(ast[1], ast[2]))
    if kind == "scalar_subquery":
        raise SqlError(
            "scalar subqueries are only supported in WHERE and HAVING"
        )
    if kind == "star":
        raise SqlError("* only allowed as a projection or inside COUNT(*)")
    raise SqlError(f"cannot compile {ast!r}")


def _ast_columns(ast) -> list[tuple[str | None, str]]:
    """All (qualifier, name) column refs in an expression ast."""
    kind = ast[0]
    if kind == "col":
        return [(ast[1], ast[2])]
    if kind in ("bin",):
        return _ast_columns(ast[2]) + _ast_columns(ast[3])
    if kind in ("and", "or"):
        return _ast_columns(ast[1]) + _ast_columns(ast[2])
    if kind == "not":
        return _ast_columns(ast[1])
    if kind == "isnull":
        return _ast_columns(ast[1])
    if kind == "agg":
        return _ast_columns(ast[2]) if ast[2] is not None else []
    if kind == "func":
        return [c for a in ast[2] for c in _ast_columns(a)]
    if kind == "case":
        operand, whens, default = ast[1], ast[2], ast[3]
        out = [] if operand is None else _ast_columns(operand)
        out += [c for (cond, then) in whens
                for c in _ast_columns(cond) + _ast_columns(then)]
        return out + _ast_columns(default)
    return []


def _split_equalities(on_ast, left_aliases: set[str], right_alias: str):
    """Split an ON expression into equi-join pairs + residual conditions.

    Returns (pairs, residual) with pairs = [(left_ast, right_ast)].
    """
    conjuncts = []

    def walk(a):
        if a[0] == "and":
            walk(a[1])
            walk(a[2])
        else:
            conjuncts.append(a)

    walk(on_ast)
    pairs, residual = [], []
    for c in conjuncts:
        if c[0] == "bin" and c[1] == "==":
            l_cols = {q for (q, _n) in _ast_columns(c[2])}
            r_cols = {q for (q, _n) in _ast_columns(c[3])}
            if l_cols <= left_aliases and r_cols == {right_alias}:
                pairs.append((c[2], c[3]))
                continue
            if r_cols <= left_aliases and l_cols == {right_alias}:
                pairs.append((c[3], c[2]))
                continue
        residual.append(c)
    return pairs, residual


def _mangle(alias: str, col: str) -> str:
    # length prefix keeps the split point unambiguous: aliases and columns
    # may themselves contain underscores
    return f"_pw{len(alias)}_{alias}_{col}"


def _table_env(table: Table, alias: str) -> _Env:
    """Working table for a single FROM item: columns mangled by alias."""
    mapping = {(alias, c): _mangle(alias, c) for c in table.column_names()}
    working = table.select(
        **{m: ColumnReference(this, c) for (al, c), m in mapping.items()}
    )
    return _Env(working, mapping)


def _compile_from(stmt: dict, tables: dict[str, Table]) -> _Env:
    def item_env(item) -> _Env:
        if item[0] == "subquery":
            sub = _compile_query(item[1], tables)
            return _table_env(sub, item[2])
        _, name, alias = item
        if name not in tables:
            raise SqlError(f"unknown table {name!r}")
        return _table_env(tables[name], alias)

    env = item_env(stmt["from_items"][0])
    for mode, item, on_ast in stmt["joins"]:
        renv = item_env(item)
        merged_qualified = dict(env.qualified)
        for k, v in renv.qualified.items():
            if k in merged_qualified:
                raise SqlError(f"duplicate table alias {k[0]!r}")
            merged_qualified[k] = v

        if mode == "cross":
            on_conds = [
                expr_mod.ColumnBinaryOpExpression(
                    "==",
                    expr_mod.ColumnConstExpression(0),
                    expr_mod.ColumnConstExpression(0),
                )
            ]
            jmode = JoinMode.INNER
            residual = []
        else:
            left_aliases = {al for (al, _c) in env.qualified}
            right_alias = next(iter({al for (al, _c) in renv.qualified}))
            pairs, residual = _split_equalities(on_ast, left_aliases, right_alias)
            if not pairs:
                raise SqlError("JOIN ... ON requires at least one equality")
            jmode = {
                "inner": JoinMode.INNER,
                "left": JoinMode.LEFT,
                "right": JoinMode.RIGHT,
                "outer": JoinMode.OUTER,
            }[mode]
            if residual and jmode is not JoinMode.INNER:
                raise SqlError(
                    "non-equality ON conditions are only supported for INNER JOIN"
                )
            on_conds = []
            for l_ast, r_ast in pairs:
                le = _rebind(_compile_scalar(l_ast, env), left_ph)
                re_ = _rebind(_compile_scalar(r_ast, renv), right_ph)
                on_conds.append(expr_mod.ColumnBinaryOpExpression("==", le, re_))

        jr = JoinResult(env.table, renv.table, on_conds, mode=jmode)
        sel = {}
        for (_al, _c), m in env.qualified.items():
            sel[m] = ColumnReference(left_ph, m)
        for (_al, _c), m in renv.qualified.items():
            sel[m] = ColumnReference(right_ph, m)
        working = jr.select(**sel)
        env = _Env(working, merged_qualified)
        for cond_ast in residual:
            env = _Env(
                env.table.filter(_compile_scalar(cond_ast, env)), env.qualified
            )
    return env


def _rebind(e: ColumnExpression, ph) -> ColumnExpression:
    """Rewrite `this`-references onto a join-side placeholder."""
    if isinstance(e, ColumnReference):
        return ColumnReference(ph, e.name)
    new = e._substitute({})
    for attr in getattr(new, "__slots__", ()):
        try:
            v = getattr(new, attr)
        except AttributeError:
            continue
        if isinstance(v, ColumnExpression):
            object.__setattr__(new, attr, _rebind(v, ph))
        elif isinstance(v, tuple) and any(isinstance(x, ColumnExpression) for x in v):
            object.__setattr__(
                new, attr, tuple(_rebind(x, ph) if isinstance(x, ColumnExpression) else x for x in v)
            )
    return new


def _has_agg(ast) -> bool:
    if ast[0] == "agg":
        return True
    if ast[0] in ("bin",):
        return _has_agg(ast[2]) or _has_agg(ast[3])
    if ast[0] in ("and", "or"):
        return _has_agg(ast[1]) or _has_agg(ast[2])
    if ast[0] in ("not", "isnull"):
        return _has_agg(ast[1])
    if ast[0] == "func":
        return any(_has_agg(a) for a in ast[2])
    if ast[0] == "case":
        operand, whens, default = ast[1], ast[2], ast[3]
        if operand is not None and _has_agg(operand):
            return True
        return any(
            _has_agg(c) or _has_agg(th) for (c, th) in whens
        ) or _has_agg(default)
    return False


# ---------------------------------------------------------------------------
# scalar subqueries (uncorrelated, WHERE/HAVING)
# ---------------------------------------------------------------------------


def _rewrite_subqueries(ast, found: list, col_kind: str):
    """Replace ``scalar_subquery`` nodes with placeholder column refs
    (qualifier ``#subqN``); collects the subquery asts in ``found``."""
    if isinstance(ast, list):
        return [_rewrite_subqueries(x, found, col_kind) for x in ast]
    if not isinstance(ast, tuple):
        return ast
    if ast[0] == "scalar_subquery":
        idx = len(found)
        found.append(ast[1])
        return (col_kind, f"#subq{idx}", "val")
    return tuple(_rewrite_subqueries(x, found, col_kind) for x in ast)


def _scalar_subquery_table(q_ast, tables: dict[str, Table]) -> Table:
    """Compile a scalar subquery; enforce single-row shape statically.

    Streams have no runtime "more than one row" error point, so the
    single-row guarantee must hold by construction: exactly one aggregate
    projection, no GROUP BY, no set operations.
    """
    scoped = dict(tables)
    body = q_ast
    while body[0] == "with":
        for name, sub in body[1]:
            scoped[name] = _compile_query(sub, scoped)
        body = body[2]
    if body[0] != "select":
        raise SqlError("scalar subquery cannot be a UNION/INTERSECT/EXCEPT")
    s = body[1]
    projs = s["projections"]
    if (
        s["group"] is not None
        or len(projs) != 1
        or projs[0][0][0] == "star"
        or not _has_agg(projs[0][0])
    ):
        raise SqlError(
            "scalar subquery must be a single aggregate projection without "
            "GROUP BY (uncorrelated)"
        )
    return _compile_select(s, scoped)


def _attach_scalar_subqueries(stmt: dict, env: _Env, tables: dict[str, Table]) -> _Env:
    """Cross-join each uncorrelated scalar subquery's single-row result
    onto the working table so WHERE/HAVING can reference it as a column."""
    found: list = []  # WHERE and HAVING placeholders share one numbering
    if stmt["where"] is not None:
        stmt["where"] = _rewrite_subqueries(stmt["where"], found, "col")
    if stmt["having"] is not None:
        stmt["having"] = _rewrite_subqueries(stmt["having"], found, "anycol")
    if not found:
        return env
    qualified = dict(env.qualified)
    working = env.table
    for i, sub_ast in enumerate(found):
        sub = _scalar_subquery_table(sub_ast, tables)
        mangled = f"_pw_subq_{i}"
        sub1 = sub.select(
            **{mangled: ColumnReference(this, sub.column_names()[0])}
        )
        always = expr_mod.ColumnBinaryOpExpression(
            "==",
            expr_mod.ColumnConstExpression(0),
            expr_mod.ColumnConstExpression(0),
        )
        jr = JoinResult(working, sub1, [always], mode=JoinMode.INNER)
        sel = {m: ColumnReference(left_ph, m) for m in working.column_names()}
        sel[mangled] = ColumnReference(right_ph, mangled)
        working = jr.select(**sel)
        qualified[(f"#subq{i}", "val")] = mangled
    return _Env(working, qualified)


def _projection_name(ast, alias: str | None, auto: list[int]) -> str:
    if alias:
        return alias
    if ast[0] == "col":
        return ast[2]
    if ast[0] == "agg":
        # COUNT(x) -> count, SUM(y) -> sum — matches common SQL defaults
        return ast[1]
    auto[0] += 1
    return f"col_{auto[0] - 1}"


def _rewrite_having_aliases(ast, alias_map: dict, env: _Env):
    """HAVING may reuse projection aliases (``SELECT SUM(v) AS s ...
    HAVING s > 3``).  A name that resolves as a source column wins (the
    standard rule); otherwise a matching projection's expression is
    substituted."""
    if isinstance(ast, list):
        return [_rewrite_having_aliases(x, alias_map, env) for x in ast]
    if not isinstance(ast, tuple):
        return ast
    if ast[0] == "col" and ast[1] is None:
        name = ast[2]
        try:
            env.resolve(None, name)
            return ast
        except SqlError:
            if name in alias_map:
                return alias_map[name]
            return ast
    return tuple(_rewrite_having_aliases(x, alias_map, env) for x in ast)


def _compile_select(stmt: dict, tables: dict[str, Table]) -> Table:
    env = _compile_from(stmt, tables)
    env = _attach_scalar_subqueries(stmt, env, tables)

    if stmt["where"] is not None:
        env = _Env(env.table.filter(_compile_scalar(stmt["where"], env)), env.qualified)

    auto = [0]
    agg_query = stmt["group"] is not None or any(
        _has_agg(e) for (e, _a) in stmt["projections"]
    )

    select_exprs: dict[str, Any] = {}

    def add_projection(name: str, expr) -> None:
        if name in select_exprs:
            raise SqlError(
                f"duplicate output column {name!r}; alias the projections"
            )
        select_exprs[name] = expr

    for e, alias in stmt["projections"]:
        if e[0] == "star":
            for out_name, mangled in env.all_columns(e[1]):
                add_projection(out_name, ColumnReference(this, mangled))
            continue
        add_projection(
            _projection_name(e, alias, auto), _compile_scalar(e, env, agg_ok=agg_query)
        )

    if not agg_query:
        result = env.table.select(**select_exprs)
        if stmt["having"] is not None:
            raise SqlError("HAVING requires GROUP BY or aggregates")
        if stmt["distinct"]:
            result = _distinct(result)
        return result

    # group keys: plain columns group directly; expressions materialize first
    work = env.table
    group_refs = []
    if stmt["group"]:
        extra = {}
        for i, g_ast in enumerate(stmt["group"]):
            if g_ast[0] == "col":
                group_refs.append(env.resolve(g_ast[1], g_ast[2]))
            else:
                gname = f"_pw_groupexpr_{i}"
                extra[gname] = _compile_scalar(g_ast, env)
                group_refs.append(ColumnReference(this, gname))
        if extra:
            work = work.with_columns(**extra)

    having_name = None
    if stmt["having"] is not None:
        alias_map = {
            (alias or (e[2] if e[0] == "col" else e[1] if e[0] == "agg" else None)): e
            for e, alias in stmt["projections"]
            if e[0] != "star"
        }
        alias_map.pop(None, None)
        having_ast = _rewrite_having_aliases(stmt["having"], alias_map, env)
        having_name = "_pw_having"
        select_exprs[having_name] = _compile_scalar(having_ast, env, agg_ok=True)

    if group_refs:
        result = work.groupby(*group_refs).reduce(**select_exprs)
    else:
        result = work.reduce(**select_exprs)
    if having_name:
        result = result.filter(ColumnReference(this, having_name)).without(having_name)
    if stmt["distinct"]:
        result = _distinct(result)
    return result


def _distinct(table: Table) -> Table:
    refs = [ColumnReference(this, n) for n in table.column_names()]
    return table.groupby(*refs).reduce(
        **{n: ColumnReference(this, n) for n in table.column_names()}
    )


def _align_columns(a: Table, b: Table) -> Table:
    """Rename ``b``'s columns positionally to ``a``'s (set-op convention:
    the first query names the output)."""
    a_names, b_names = a.column_names(), b.column_names()
    if len(a_names) != len(b_names):
        raise SqlError(
            f"set operation arity mismatch: {len(a_names)} vs {len(b_names)} columns"
        )
    if a_names == b_names:
        return b
    return b.select(
        **{an: ColumnReference(this, bn) for an, bn in zip(a_names, b_names)}
    )


def _set_op(a: Table, b: Table, keep: str) -> Table:
    """Value-based INTERSECT / EXCEPT with distinct set semantics.

    Tag rows by side, concat, group by every value column, keep groups by
    side-presence.  Grouping (not joining) makes NULLs compare equal, the
    SQL set-operation rule that a join-based plan would violate.
    """
    b = _align_columns(a, b)
    names = a.column_names()
    ta = a.with_columns(_pw_setl=1, _pw_setr=0)
    tb = b.with_columns(_pw_setl=0, _pw_setr=1)
    both = ta.concat_reindex(tb)
    refs = [ColumnReference(this, n) for n in names]
    grouped = both.groupby(*refs).reduce(
        **{n: ColumnReference(this, n) for n in names},
        _pw_setl=reducers.sum(ColumnReference(this, "_pw_setl")),
        _pw_setr=reducers.sum(ColumnReference(this, "_pw_setr")),
    )
    l_ref = ColumnReference(this, "_pw_setl")
    r_ref = ColumnReference(this, "_pw_setr")
    if keep == "intersect":
        cond = (l_ref > 0) & (r_ref > 0)
    else:  # except
        cond = (l_ref > 0) & (r_ref == 0)
    return grouped.filter(cond).without("_pw_setl", "_pw_setr")


def _compile_query(ast, tables: dict[str, Table]) -> Table:
    if ast[0] == "with":
        # CTEs: each is visible to later CTEs and the body; user tables of
        # the same name are shadowed for this query only
        scoped = dict(tables)
        for name, sub in ast[1]:
            scoped[name] = _compile_query(sub, scoped)
        return _compile_query(ast[2], scoped)
    if ast[0] == "select":
        return _compile_select(ast[1], tables)
    _, stmts, ops = ast
    # standard SQL precedence: INTERSECT binds tighter than UNION/EXCEPT
    items: list[Table] = [_compile_select(s, tables) for s in stmts]
    folded: list[Table] = [items[0]]
    fold_ops: list[tuple[str, str]] = []
    for (op, mode), nxt in zip(ops, items[1:]):
        if op == "intersect":
            folded[-1] = _set_op(folded[-1], nxt, "intersect")
        else:
            fold_ops.append((op, mode))
            folded.append(nxt)
    result = folded[0]
    for (op, mode), nxt in zip(fold_ops, folded[1:]):
        if op == "except":
            result = _set_op(result, nxt, "except")
        else:
            result = result.concat_reindex(_align_columns(result, nxt))
            if mode == "distinct":
                result = _distinct(result)
    return result


def sql(query: str, **tables: Table) -> Table:
    r"""Execute a SQL query over the provided tables.

    Reference: ``pw.sql`` (`internals/sql.py:613`).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('k | v\na | 1\na | 2\nb | 5')
    >>> r = pw.sql('SELECT k, SUM(v) AS s FROM t GROUP BY k', t=t)
    >>> pw.debug.compute_and_print(r, include_id=False)
    k | s
    a | 3
    b | 5
    """
    p = _Parser(_tokenize(query.strip().rstrip(";")))
    ast = _parse_query(p)
    if p.peek()[0] != "end":
        raise SqlError(f"unexpected trailing tokens: {p.peek()!r}")
    return _compile_query(ast, tables)
