"""Live console monitoring dashboard.

Parity target: ``python/pathway/internals/monitoring.py:165-273`` —
``MonitoringLevel``, ``StatsMonitor`` and ``monitor_stats``: a
rich-powered live view with connector/operator rows (latency, row
counts) plus a tail of recent log lines, refreshed from each
``ProberStats`` snapshot the engine prober publishes.
"""

from __future__ import annotations

import enum
import logging
import sys
from contextlib import contextmanager
from typing import Any

from pathway_tpu.engine.probes import OperatorStats, ProberStats


class MonitoringLevel(enum.Enum):
    """What the console dashboard shows (reference ``monitoring.py:228``)."""

    AUTO = 0  # IN_OUT when stderr is a tty, NONE otherwise
    AUTO_ALL = 1  # ALL when stderr is a tty, NONE otherwise
    NONE = 2
    IN_OUT = 3  # inputs + outputs only
    ALL = 4  # every operator

    def resolve(self, interactive: bool | None = None) -> "MonitoringLevel":
        if interactive is None:
            interactive = sys.stderr.isatty()
        if self == MonitoringLevel.AUTO:
            return MonitoringLevel.IN_OUT if interactive else MonitoringLevel.NONE
        if self == MonitoringLevel.AUTO_ALL:
            return MonitoringLevel.ALL if interactive else MonitoringLevel.NONE
        return self


class _LogBuffer(logging.Handler):
    """Keeps the last N log lines for the dashboard footer."""

    def __init__(self, limit: int = 10):
        super().__init__()
        self.limit = limit
        self.lines: list[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self.lines.append(self.format(record))
        except Exception:  # pragma: no cover - formatting failure
            return
        del self.lines[: -self.limit]


class StatsMonitor:
    """Renders ProberStats snapshots as a live table (reference ``StatsMonitor``)."""

    def __init__(
        self,
        level: MonitoringLevel = MonitoringLevel.IN_OUT,
        *,
        console: Any = None,
        refresh_per_second: int = 4,
    ):
        from rich.console import Console

        self.level = level
        self.console = console or Console(file=sys.stderr)
        self.refresh_per_second = refresh_per_second
        self.stats: ProberStats = ProberStats()
        self.log_buffer = _LogBuffer()
        self.log_buffer.setFormatter(logging.Formatter("%(levelname)s %(message)s"))
        self._live = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "StatsMonitor":
        from rich.live import Live

        logging.getLogger("pathway_tpu").addHandler(self.log_buffer)
        self._live = Live(
            self._render(),
            console=self.console,
            refresh_per_second=self.refresh_per_second,
            transient=False,
        )
        self._live.start()
        return self

    def update(self, stats: ProberStats) -> None:
        self.stats = stats
        if self._live is not None:
            self._live.update(self._render())

    def close(self) -> None:
        if self._live is not None:
            self._live.update(self._render(final=True))
            self._live.stop()
            self._live = None
        logging.getLogger("pathway_tpu").removeHandler(self.log_buffer)

    # -- rendering ---------------------------------------------------------
    def _rows(self) -> list[tuple[str, OperatorStats]]:
        s = self.stats
        rows: list[tuple[str, OperatorStats]] = [
            ("input", s.input_stats),
            ("output", s.output_stats),
        ]
        # per-connector ingestion rows (connectors/monitoring.rs analog)
        for c in s.connector_stats:
            rows.append(
                (
                    f"src:{c.name}",
                    OperatorStats(name=c.name, rows_in=c.rows, rows_out=c.rows, done=c.finished),
                )
            )
        if self.level == MonitoringLevel.ALL:
            rows += [(f"{op.name}#{oid}", op) for oid, op in s.operator_stats.items()]
        return rows

    def _runtime_summary(self) -> str | None:
        """One-line comm/persistence health from the unified metrics
        registry (``engine/metrics.py``) — the dashboard's view of the
        same numbers ``/metrics`` and the OTLP exporter serve."""
        from pathway_tpu.engine import metrics as _metrics

        scalars = _metrics.get_registry().scalar_metrics()

        def total(prefix: str) -> float:
            return sum(
                v for k, v in scalars.items()
                if k == prefix or k.startswith(prefix + "{")
            )

        def peak(prefix: str) -> float | None:
            # quantile gauges must not SUM across label children (a p95 is
            # not additive) — report the worst child instead
            vals = [
                v for k, v in scalars.items()
                if k == prefix or k.startswith(prefix + "{")
            ]
            return max(vals) if vals else None

        parts: list[str] = []
        stale = peak("output.staleness.s")
        if stale is not None:
            # worst-output freshness: how old is the newest data any
            # output reflects right now (engine/freshness.py) — rising
            # here with a flat epoch p95 means a starved source, not a
            # slow pipeline
            parts.append(f"staleness: {stale:.2f} s (worst output)")
        epoch_p95 = peak("epoch.duration.ms.p95")
        if epoch_p95 is not None:
            parts.append(f"epoch p95: {epoch_p95:.1f} ms")
        compiles = total("jax.compile.count")
        if compiles:
            parts.append(
                f"jit: {int(compiles)} compile(s) / "
                f"{int(total('jax.cache.miss'))} cache miss(es)"
            )
        from pathway_tpu.engine.telemetry import (
            DEVICE_PADDING_WASTE_FRACTION,
            DEVICE_UTILIZATION,
        )

        batches = total("device.dispatch.batches")
        if batches:
            # the device story in one clause: how busy, how wasteful —
            # the full panel lives in `pathway_tpu top`
            device = f"device: {int(batches)} batch(es)"
            util = peak(DEVICE_UTILIZATION)
            if util:
                from pathway_tpu.device.telemetry import format_utilization

                device += f", {format_utilization(util)} of peak"
            waste = peak(DEVICE_PADDING_WASTE_FRACTION)
            if waste:
                device += f", {waste:.1%} padding"
            parts.append(device)
        frames = total("comm.frames.sent")
        if frames:
            mb = total("comm.bytes.sent") / (1 << 20)
            comm = f"comm: {int(frames)} frames / {mb:.1f} MiB sent"
            reconnects = total("comm.reconnects")
            if reconnects:
                comm += f", {int(reconnects)} reconnect(s)"
            parts.append(comm)
        commits = total("checkpoint.commits")
        if commits:
            ckpt = (
                f"checkpoint: {int(commits)} commit(s) / "
                f"{total('checkpoint.bytes') / (1 << 20):.1f} MiB"
            )
            inflight = total("checkpoint.inflight.bytes")
            if inflight:
                ckpt += f", {inflight / (1 << 20):.1f} MiB in flight"
            parts.append(ckpt)
        dropped = total("telemetry.export.dropped")
        if dropped:
            parts.append(f"telemetry: {int(dropped)} export(s) dropped")
        return " · ".join(parts) if parts else None

    def _render(self, final: bool = False):
        from rich.console import Group
        from rich.table import Table as RichTable
        from rich.text import Text

        table = RichTable(title=None, expand=False)
        table.add_column("operator")
        table.add_column("epoch", justify="right")
        table.add_column("lag (ms)", justify="right")
        table.add_column("rows in", justify="right")
        table.add_column("rows out", justify="right")
        table.add_column("step (ms)", justify="right")
        table.add_column("errors", justify="right")
        for name, op in self._rows():
            table.add_row(
                name + (" [done]" if op.done else ""),
                "-" if op.time is None else str(op.time),
                "-" if op.lag_ms is None else f"{op.lag_ms:.0f}",
                str(op.rows_in),
                str(op.rows_out),
                f"{op.step_ms:.1f}",
                str(op.errors) if op.errors else "-",
            )
        header = Text(
            f"epochs: {self.stats.epochs}"
            + ("  (finished)" if final else "")
        )
        parts: list[Any] = [header, table]
        summary = self._runtime_summary()
        if summary:
            parts.append(Text(summary))
        if self.log_buffer.lines:
            parts.append(Text("\n".join(self.log_buffer.lines[-5:])))
        return Group(*parts)


@contextmanager
def monitor_stats(
    level: MonitoringLevel,
    *,
    console: Any = None,
    interactive: bool | None = None,
):
    """Context manager yielding a stats callback (or None if monitoring is off).

    Mirrors ``monitor_stats`` (reference ``monitoring.py:226``): resolves
    AUTO levels against tty-ness, runs the live dashboard for the duration.
    """
    resolved = level.resolve(interactive)
    if resolved == MonitoringLevel.NONE:
        yield None
        return
    monitor = StatsMonitor(resolved, console=console).start()
    try:
        yield monitor
    finally:
        monitor.close()
