from pathway_tpu.internals import dtype
from pathway_tpu.internals.schema import (
    ColumnDefinition,
    Schema,
    SchemaProperties,
    column_definition,
    schema_builder,
    schema_from_csv,
    schema_from_dict,
    schema_from_types,
)
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    apply,
    apply_async,
    apply_with_type,
    assert_table_has_schema,
    cast,
    coalesce,
    declare_type,
    fill_error,
    if_else,
    make_tuple,
    require,
    unwrap,
)
from pathway_tpu.internals.table import (
    GroupedTable,
    Joinable,
    JoinMode,
    JoinResult,
    Table,
    TableLike,
    TableSlice,
    groupby,
    join,
    join_inner,
    join_left,
    join_outer,
    join_right,
)
from pathway_tpu.internals.thisclass import left, right, this
