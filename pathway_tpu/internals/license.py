"""License keys and entitlement gates.

Parity target: ``src/engine/license.rs`` — three license shapes:

* no key → no entitlements (free tier; everything core still works),
* an offline license file (``-----BEGIN LICENSE FILE-----``), an
  ed25519-signed JSON payload carrying entitlements / policy /
  ``telemetry_required`` (``license.rs:25`` ``base64+ed25519``),
* a plain license key, validated against a license server in the
  reference (``license.rs:22``) — this build has no egress, so plain
  keys resolve against the built-in demo-key registry instead and
  anything unknown fails entitlement checks with the same error type.

Entitlement names are case-insensitive (uppercased, ``license.rs:60``).
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field

LICENSE_ALGORITHM = "base64+ed25519"
_FILE_HEADER = "-----BEGIN LICENSE FILE-----"
_FILE_FOOTER = "-----END LICENSE FILE-----"

# verifying key for offline license files (hex, 32 bytes).  Generated for
# this framework; see tests for the signing half used in fixtures.
PUBLIC_KEY = "de259851694be86bf8d9d9c11104f0a9a5c74fbdc96ef4613ed375fd44e7c338"

# demo keys (the reference's publicly distributed monitoring keys resolve
# server-side; with zero egress the registry is local)
_DEMO_KEY_PREFIX = "demo-license-key-with-telemetry"
_DEMO_ENTITLEMENTS = frozenset({"XPACK-SPATIAL", "MONITORING", "TELEMETRY"})


class LicenseError(Exception):
    pass


class InsufficientLicenseError(LicenseError):
    def __init__(self, entitlements: list[str]):
        super().__init__(
            "one of the features you used requires upgrading your Pathway "
            f"license (missing entitlements: {', '.join(entitlements)})"
        )
        self.entitlements = entitlements


@dataclass(frozen=True)
class License:
    key: str = ""
    entitlements: frozenset[str] = frozenset()
    telemetry_required: bool = False
    policy: str = ""
    offline: bool = False

    @classmethod
    def new(cls, license_key: str | None) -> "License":
        key = (license_key or "").strip()
        if not key:
            return cls()
        if key.startswith(_FILE_HEADER):
            return _parse_offline_license(key)
        if key.startswith(_DEMO_KEY_PREFIX):
            return cls(
                key=key, entitlements=_DEMO_ENTITLEMENTS, telemetry_required=True
            )
        # unknown plain key: kept (its shortcut is reported in telemetry)
        # but grants nothing without the license server
        return cls(key=key)

    def check_entitlements(self, entitlements: list[str] | str) -> None:
        if isinstance(entitlements, str):
            entitlements = [entitlements]
        wanted = [e.upper() for e in entitlements]
        if not all(e in self.entitlements for e in wanted):
            raise InsufficientLicenseError(wanted)

    def has_entitlement(self, entitlement: str) -> bool:
        return entitlement.upper() in self.entitlements

    def shortcut(self) -> str:
        """First two dash-separated groups of a well-formed key (license.rs:92)."""
        parts = self.key.split("-")
        if len(parts) >= 5 and all(parts[:5]):
            return f"{parts[0]}-{parts[1]}"
        return ""


def _parse_offline_license(text: str) -> License:
    """Verify and decode an offline license file.

    Format (keygen-style, matching the reference's dependency): the body is
    base64 of ``{"enc": <base64 payload>, "sig": <base64 ed25519 signature
    over b"license/" + enc>, "alg": "base64+ed25519"}``; the payload JSON
    carries ``entitlements`` (list), ``policy``, ``telemetry_required``.
    """
    body = text.strip()
    if body.startswith(_FILE_HEADER):
        body = body[len(_FILE_HEADER):]
    if body.endswith(_FILE_FOOTER):
        body = body[: -len(_FILE_FOOTER)]
    try:
        outer = json.loads(base64.b64decode("".join(body.split())))
        enc, sig, alg = outer["enc"], outer["sig"], outer.get("alg", "")
    except Exception as exc:
        raise LicenseError(f"malformed license file: {exc}") from exc
    if alg != LICENSE_ALGORITHM:
        raise LicenseError(f"unsupported license algorithm {alg!r}")
    try:
        # optional dependency, guarded like trace.py's add_note shim: the
        # cryptography wheel is preferred when present, but its absence
        # degrades to the pure-Python RFC 8032 verifier — never to an
        # ImportError that takes unrelated license paths down with it
        try:
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PublicKey,
            )
        except ImportError:
            from pathway_tpu.internals import _ed25519

            if not _ed25519.verify(
                bytes.fromhex(PUBLIC_KEY),
                base64.b64decode(sig),
                b"license/" + enc.encode(),
            ):
                raise LicenseError("license signature verification failed")
        else:
            verifier = Ed25519PublicKey.from_public_bytes(
                bytes.fromhex(PUBLIC_KEY)
            )
            verifier.verify(base64.b64decode(sig), b"license/" + enc.encode())
    except LicenseError:
        raise
    except Exception as exc:
        raise LicenseError(f"license signature verification failed: {exc}") from exc
    try:
        payload = json.loads(base64.b64decode(enc))
    except Exception as exc:
        raise LicenseError(f"malformed license payload: {exc}") from exc
    return License(
        key="",
        entitlements=frozenset(e.upper() for e in payload.get("entitlements", [])),
        telemetry_required=bool(payload.get("telemetry_required", False)),
        policy=str(payload.get("policy", "")),
        offline=True,
    )
