"""Columnar (vectorized) expression compilation for large epoch batches.

SURVEY.md §7.3: the host hot path should move columnar batches, not Python
row tuples.  The engine stays delta-correct and row-oriented at its edges;
inside an epoch, ``ExprNode``/``FilterNode``/``GroupByNode`` switch to a
numpy fast path when (a) the expression compiles to vector ops and (b) the
batch's columns materialize as typed 1-D arrays (no ``None``/``Error``
values, no mixed types).  Anything else falls back to the per-row
interpreter — semantics are identical by construction, because the fast
path *bails* (``VecBail``) rather than approximating:

* division/modulo with any zero divisor bails (per-row path yields ERROR
  for exactly the offending rows);
* ``**`` on ints bails (Python bignum semantics ≠ int64);
* columns containing None/Error/mixed types materialize as object arrays
  and bail.

Int arithmetic runs in int64 — the reference engine's own integer type
(``Value::Int`` is ``i64``, value.rs:210) — with overflow surfaced by
numpy where detectable.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import (
    CastExpression,
    CoalesceExpression,
    ColumnBinaryOpExpression,
    ColumnConstExpression,
    ColumnExpression,
    ColumnReference,
    ColumnUnaryOpExpression,
    ConvertExpression,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    MakeTupleExpression,
    UnwrapExpression,
)
from pathway_tpu.internals.thisclass import ThisPlaceholder

VEC_THRESHOLD = 64  # below this, per-row beats transpose + dispatch


def _env_enabled() -> bool:
    # declared kill switch (PATHWAY_COLUMNAR): ops can force the row-wise
    # reference path fleet-wide without a code change
    try:
        from pathway_tpu.internals.config import env_bool

        return env_bool("PATHWAY_COLUMNAR")
    except Exception:  # noqa: BLE001 - config must never break compilation
        return True


# process-wide switch (benchmark baselines, debugging); the row path is the
# reference semantics, the vector path must be observationally identical
ENABLED = _env_enabled()


def set_enabled(flag: bool) -> None:
    global ENABLED
    ENABLED = bool(flag)

VecFn = Callable[[dict, int], np.ndarray]  # (columns by index, n) -> array


class VecBail(Exception):
    """Data-dependent condition the vector path cannot honor; caller falls
    back to the per-row interpreter for this batch."""


# ---------------------------------------------------------------------------
# bail accounting: every fall-back from a columnar fast path to the row-wise
# evaluator is counted per (operator, reason) — silent bails were invisible
# before, so a pipeline could quietly run 5x slower than its benchmark twin.
# Mirrored two ways: the metrics registry (`columnar.bail.count{op=,reason=}`,
# /status + `pathway_tpu top`) and a process-local Counter the profiler
# snapshot embeds (`pathway_tpu profile` renders the top reasons).
# ---------------------------------------------------------------------------

BAIL_COUNTS: _Counter = _Counter()

_bail_children: dict[tuple[str, str], Any] = {}


def note_bail(op: str, reason: str) -> None:
    """Record one columnar→row fall-back of operator kind ``op``."""
    BAIL_COUNTS[(op, reason)] += 1
    child = _bail_children.get((op, reason))
    if child is None:
        try:
            from pathway_tpu.engine import metrics as _metrics

            child = _metrics.get_registry().counter(
                "columnar.bail.count",
                "columnar fast-path batches that fell back to the row-wise "
                "evaluator",
                op=op,
                reason=reason,
            )
        except Exception:  # noqa: BLE001 - accounting must never break a step
            return
        _bail_children[(op, reason)] = child
    child.inc()


def bail_snapshot(top: int = 8) -> list[dict[str, Any]]:
    """Top bail reasons for profiler snapshots / post-mortems."""
    return [
        {"op": op, "reason": reason, "count": count}
        for (op, reason), count in BAIL_COUNTS.most_common(top)
    ]


def _const_array(v, n: int) -> np.ndarray:
    return np.full(n, v)


def passthrough_index(e, binder) -> int | None:
    """Source-column index when ``e`` is a bare same-table column reference
    (the dominant ``with_columns`` shape).  The columnar path then copies
    the value straight from the input row — no materialization, no
    array↔scalar conversions — which also means dirty columns (None/Error/
    mixed) no longer force the whole node onto the row path.  Values in
    rows are already coerced to their column dtypes, so the copy matches
    the row path's ``dt.coerce`` identity bit-for-bit."""
    if isinstance(e, ColumnReference):
        tbl = e.table
        if (
            (isinstance(tbl, ThisPlaceholder) or tbl is binder.table)
            and e.name != "id"
            and e.name in binder.col_index
        ):
            return binder.col_index[e.name]
    return None


def affine_index(e, binder) -> tuple[int, int | float] | None:
    """``(col_idx, const_offset)`` when ``e`` is a same-table column plus/
    minus a numeric constant (the shape every temporal threshold lowers to:
    ``time``, ``time + delay``, ``end + cutoff``).  The temporal operators'
    columnar path then evaluates the whole epoch's times/thresholds as one
    array op.  None for anything else — the row path stays the oracle."""
    idx = passthrough_index(e, binder)
    if idx is not None:
        return idx, 0
    if isinstance(e, ColumnBinaryOpExpression) and e._op in ("+", "-"):
        left, right = e._left, e._right
        lidx = passthrough_index(left, binder)
        if (
            lidx is not None
            and isinstance(right, ColumnConstExpression)
            and type(right._val) in (int, float)
        ):
            off = right._val
            return lidx, (-off if e._op == "-" else off)
        ridx = passthrough_index(right, binder)
        if (
            e._op == "+"
            and ridx is not None
            and isinstance(left, ColumnConstExpression)
            and type(left._val) in (int, float)
        ):
            return ridx, left._val
    return None


def affine_values(
    cols: dict[int, np.ndarray], idx: int, offset: int | float
) -> np.ndarray:
    """Apply an :func:`affine_index` offset to a materialized column with
    row-path exactness: numeric columns only, int offsets guarded against
    int64 wrap (the row path adds Python bignums)."""
    arr = cols[idx]
    if arr.dtype.kind not in "if":
        raise VecBail
    if offset == 0 and isinstance(offset, int):
        return arr
    if arr.dtype.kind == "i" and isinstance(offset, int):
        if _abs_bound(arr) + abs(offset) > _I64_MAX:
            raise VecBail
    return arr + offset


def split_deltas(deltas: list, mask) -> tuple[list, list]:
    """Partition a delta list by a uint8/bool mask (kept, dropped), rows
    untouched — the batched form of the temporal buffers' release scan.
    Native single pass when available."""
    sd = _native_sym("split_deltas")
    if sd is not None:
        return sd(deltas, np.ascontiguousarray(mask, dtype=np.uint8))
    kept: list = []
    dropped: list = []
    for d, keep in zip(deltas, np.asarray(mask).tolist()):
        (kept if keep else dropped).append(d)
    return kept, dropped


def freeze_scan(
    t: np.ndarray, thr: np.ndarray, watermark
) -> tuple[bytearray, Any]:
    """FreezeNode's sequential admit/advance scan over one epoch batch:
    a row is kept unless ``thr <= watermark``; kept rows advance the
    watermark to ``max(watermark, t)`` *as the scan runs* (later rows see
    earlier rows' watermark).  Returns ``(keep mask, new watermark)``.

    Native single pass (GIL-released) when available; the Python loop over
    unboxed scalars is the fallback and matches the row path exactly."""
    fs = _native_sym("freeze_scan")
    if (
        fs is not None
        and t.dtype.kind == thr.dtype.kind
        and t.dtype.kind in "if"
        and t.dtype.itemsize == 8
        and thr.dtype.itemsize == 8
    ):
        kind = "q" if t.dtype.kind == "i" else "d"
        wm = watermark
        if wm is not None and kind == "q" and (
            not isinstance(wm, int) or not (-(2**63) <= wm < 2**63)
        ):
            fs = None  # mixed/bignum watermark: take the exact scalar loop
        elif wm is not None and kind == "d" and not isinstance(wm, float):
            fs = None
        if fs is not None:
            return fs(
                kind,
                np.ascontiguousarray(t),
                np.ascontiguousarray(thr),
                wm,
            )
    tl = t.tolist()
    thl = thr.tolist()
    wm = watermark
    mask = bytearray(len(tl))
    for i in range(len(tl)):
        if wm is not None and thl[i] <= wm:
            continue
        if wm is None or tl[i] > wm:
            wm = tl[i]
        mask[i] = 1
    return mask, wm


def try_compile_vec(e: ColumnExpression, binder) -> tuple[VecFn, set[int]] | None:
    """Compile to a columnar evaluator, or None if not vectorizable.

    ``binder`` is the row binder (needs ``table``, ``col_index``).  Returns
    (fn, needed_column_indices).
    """
    needed: set[int] = set()
    fn = _compile(e, binder, needed)
    if fn is None:
        return None
    return fn, needed


def _compile(e, binder, needed: set[int]) -> VecFn | None:
    if isinstance(e, ColumnConstExpression):
        v = e._val
        if isinstance(v, (bool, int, float, str)):
            return lambda cols, n: _const_array(v, n)
        return None

    if isinstance(e, ColumnReference):
        tbl = e.table
        if not (isinstance(tbl, ThisPlaceholder) or tbl is binder.table):
            return None  # foreign/fetched columns use the row path
        if e.name == "id" or e.name not in binder.col_index:
            return None
        idx = binder.col_index[e.name]
        needed.add(idx)
        return lambda cols, n: cols[idx]

    if isinstance(e, ColumnBinaryOpExpression):
        lf = _compile(e._left, binder, needed)
        rf = _compile(e._right, binder, needed)
        if lf is None or rf is None:
            return None
        op = e._op
        return _bin_vec(op, lf, rf)

    if isinstance(e, ColumnUnaryOpExpression):
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        if e._op == "-":

            def neg(cols, n):
                v = f(cols, n)
                if v.dtype.kind not in "if":
                    raise VecBail
                return -v

            return neg
        if e._op == "~":

            def inv(cols, n):
                v = f(cols, n)
                if v.dtype.kind == "b":
                    return ~v
                if v.dtype.kind == "i":
                    return ~v
                raise VecBail

            return inv
        return None

    if isinstance(e, IfElseExpression):
        cf = _compile(e._if, binder, needed)
        tf = _compile(e._then, binder, needed)
        ff = _compile(e._else, binder, needed)
        if cf is None or tf is None or ff is None:
            return None

        def where(cols, n):
            c = cf(cols, n)
            if c.dtype.kind != "b":
                raise VecBail
            return np.where(c, tf(cols, n), ff(cols, n))

        return where

    if isinstance(e, IsNoneExpression):
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        # typed columns cannot hold None
        return lambda cols, n: np.zeros(n, bool)

    if isinstance(e, IsNotNoneExpression):
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        return lambda cols, n: np.ones(n, bool)

    if isinstance(e, CoalesceExpression):
        f = _compile(e._args[0], binder, needed)
        return f  # typed first arg is never None

    if isinstance(e, UnwrapExpression):
        return _compile(e._expr, binder, needed)

    if isinstance(e, MakeTupleExpression):
        fs = [_compile(a, binder, needed) for a in e._args]
        if any(f is None for f in fs):
            return None

        def mk(cols, n):
            lists = []
            for f in fs:
                v = f(cols, n)
                if isinstance(v, np.ndarray):
                    # a shared-NaN object groups rows on the row path but
                    # tolist() would mint distinct NaNs — bail to keep
                    # group-key equality semantics identical
                    if v.dtype.kind == "f" and np.isnan(v).any():
                        raise VecBail
                    lists.append(v.tolist())
                else:
                    lists.append(list(v))
            return list(zip(*lists)) if lists else [()] * n

        return mk

    if isinstance(e, CastExpression):  # Convert (from Json) stays row-wise
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        target = e._return_type.strip_optional()
        if target is dt.INT:

            def to_int(cols, n):
                v = f(cols, n)
                if v.dtype.kind not in "bif":
                    raise VecBail
                return v.astype(np.int64)

            return to_int
        if target is dt.FLOAT:

            def to_float(cols, n):
                v = f(cols, n)
                if v.dtype.kind not in "bif":
                    raise VecBail
                return v.astype(np.float64)

            return to_float
        if target is dt.BOOL:

            def to_bool(cols, n):
                v = f(cols, n)
                if v.dtype.kind != "b":
                    raise VecBail
                return v

            return to_bool
        return None

    return None


_NUMERIC = "bif"

_I64_MAX = 2**63 - 1


def _abs_bound(arr: np.ndarray) -> int:
    """Largest |value| in an int array, computed safely in Python ints."""
    if arr.size == 0:
        return 0
    return max(abs(int(arr.max())), abs(int(arr.min())))


def _guard_int_overflow(op: str, lv: np.ndarray, rv: np.ndarray) -> None:
    """numpy int64 wraps silently; the row path uses Python bignums — any
    result that could exceed i64 must bail to the row interpreter."""
    if lv.dtype.kind != "i" and rv.dtype.kind != "i":
        return
    m1, m2 = _abs_bound(lv), _abs_bound(rv)
    if op in ("+", "-"):
        if m1 + m2 > _I64_MAX:
            raise VecBail
    elif op == "*":
        if m1 and m2 and m1 * m2 > _I64_MAX:
            raise VecBail


def _bin_vec(op: str, lf: VecFn, rf: VecFn) -> VecFn:
    def run(cols, n):
        lv = lf(cols, n)
        rv = rf(cols, n)
        lk, rk = lv.dtype.kind, rv.dtype.kind
        if op in ("==", "!="):
            if (lk == "U") != (rk == "U"):
                raise VecBail  # str vs non-str: row semantics return False/True
            res = lv == rv if op == "==" else lv != rv
            return res
        if op in ("<", "<=", ">", ">="):
            if lk == "U" and rk == "U":
                pass  # lexicographic, matches Python
            elif lk not in _NUMERIC or rk not in _NUMERIC:
                raise VecBail
            if op == "<":
                return lv < rv
            if op == "<=":
                return lv <= rv
            if op == ">":
                return lv > rv
            return lv >= rv
        if op in ("&", "|", "^"):
            if lk == "b" and rk == "b":
                return {"&": lv & rv, "|": lv | rv, "^": lv ^ rv}[op]
            if lk == "i" and rk == "i":
                return {"&": lv & rv, "|": lv | rv, "^": lv ^ rv}[op]
            raise VecBail
        if lk not in _NUMERIC or rk not in _NUMERIC:
            raise VecBail
        if op == "+":
            _guard_int_overflow(op, lv, rv)
            return lv + rv
        if op == "-":
            _guard_int_overflow(op, lv, rv)
            return lv - rv
        if op == "*":
            _guard_int_overflow(op, lv, rv)
            return lv * rv
        if op == "/":
            if np.any(rv == 0):
                raise VecBail  # per-row path poisons exactly those rows
            return lv / rv
        if op == "//":
            if np.any(rv == 0):
                raise VecBail
            return lv // rv
        if op == "%":
            if np.any(rv == 0):
                raise VecBail
            return lv % rv
        if op == "**":
            if lk in "bi" and rk in "bi":
                raise VecBail  # Python bignum semantics
            return lv**rv
        raise VecBail

    return run


_NAT_KIND_DTYPE = {"q": np.int64, "d": np.float64, "?": np.bool_}

_native_syms: dict[str, Any] | None = None


def _native_sym(name: str):
    """Memoized lookup of a native-core function (None when unavailable) —
    the hot paths below must not pay an import + getattr per epoch batch."""
    global _native_syms
    if _native_syms is None:
        syms: dict[str, Any] = {}
        try:
            from pathway_tpu import native as _nat

            mod = _nat.get()
            for n in (
                "materialize_columns",
                "rebuild_delta_rows",
                "filter_deltas",
                "group_indices",
                "delta_diffs",
                "split_deltas",
                "freeze_scan",
                "route_deltas",
            ):
                syms[n] = getattr(mod, n, None)
        except Exception:
            syms = {}
        _native_syms = syms
    return _native_syms.get(name)


def _get_native_materialize():
    return _native_sym("materialize_columns")


def _wrap_native_cols(res: dict) -> dict[int, np.ndarray]:
    return {
        i: (
            np.asarray(payload)
            if kind == "U"
            else np.frombuffer(payload, dtype=_NAT_KIND_DTYPE[kind])
        )
        for i, (kind, payload) in res.items()
    }


def materialize_delta_columns(
    deltas: list, needed: set[int]
) -> dict[int, np.ndarray] | None:
    """materialize_columns straight from a delta list (no rows listcomp) —
    the native single-pass when available."""
    nm = _get_native_materialize()
    if nm is not None:
        res = nm(deltas, tuple(needed), True)
        return None if res is None else _wrap_native_cols(res)
    return materialize_columns([r for (_, r, _) in deltas], needed)


def materialize_delta_columns_raw(deltas: list, needed: set[int]):
    """Native raw form ``{idx: (kind, payload)}`` — str columns stay Python
    lists (no U-array build), which the hash-grouping path wants.  Returns
    ``NotImplemented`` when the native core is unavailable."""
    nm = _get_native_materialize()
    if nm is None:
        return NotImplemented
    return nm(deltas, tuple(needed), True)


def wrap_native_col(kind: str, payload) -> np.ndarray:
    if kind == "U":
        return np.asarray(payload)
    return np.frombuffer(payload, dtype=_NAT_KIND_DTYPE[kind])


def group_indices(values: list) -> tuple[list, np.ndarray]:
    """(uniques, inverse) by hash grouping — np.unique(return_inverse)
    without the sort or the U-array conversion.  Uniques are in first-seen
    order (callers must not rely on sortedness)."""
    gi = _native_sym("group_indices")
    if gi is not None:
        uniques, inv = gi(values)
        return uniques, np.frombuffer(inv, np.int64)
    index: dict = {}
    inv = np.empty(len(values), np.int64)
    uniques: list = []
    for i, v in enumerate(values):
        pos = index.get(v)
        if pos is None:
            pos = index[v] = len(uniques)
            uniques.append(v)
        inv[i] = pos
    return uniques, inv


def delta_diffs(deltas: list) -> np.ndarray:
    """int64 diffs column of a delta list (native single pass)."""
    dd = _native_sym("delta_diffs")
    if dd is not None:
        buf = dd(deltas)
        if buf is not None:
            return np.frombuffer(buf, np.int64)
    return np.asarray([d for (_, _, d) in deltas], np.int64)


_NAT_DTYPE_KIND = {"i": "q", "f": "d", "b": "?"}


def rebuild_delta_rows(deltas: list, out_cols: list, n: int) -> list:
    """Zip result columns back into (key, row_tuple, diff) deltas, reusing
    the input keys/diffs.  ``out_cols`` entries are ndarrays or
    ``("P", src_idx)`` passthrough markers (copied from the input row).
    Native single pass when available; the Python fallback is the
    semantics reference (tolist -> zip)."""
    rb = _native_sym("rebuild_delta_rows")
    if rb is not None:
        packed = []
        for arr in out_cols:
            if isinstance(arr, tuple):  # ("P", src_idx)
                packed.append(arr)
                continue
            kind = _NAT_DTYPE_KIND.get(arr.dtype.kind)
            if kind is not None and arr.dtype.itemsize in (1, 8):
                packed.append((kind, np.ascontiguousarray(arr)))
            else:  # U / object / narrow dtypes: go through Python scalars
                packed.append(("U", arr.tolist()))
        return rb(deltas, packed)
    def _as_list(arr):
        if isinstance(arr, tuple):
            if arr[0] == "U":  # pre-built Python values (tuple columns)
                return arr[1]
            return [row[arr[1]] for (_, row, _) in deltas]  # ("P", idx)
        return arr.tolist()

    out_lists = [_as_list(arr) for arr in out_cols]
    out_rows = list(zip(*out_lists)) if out_lists else [()] * n
    return [
        (key, new_row, diff)
        for (key, _, diff), new_row in zip(deltas, out_rows)
    ]


def filter_deltas(deltas: list, mask: np.ndarray, n_cols: int) -> list:
    """Keep deltas where ``mask`` is true, truncating rows to ``n_cols``.
    Native single pass when available."""
    fd = _native_sym("filter_deltas")
    if fd is not None:
        return fd(deltas, np.ascontiguousarray(mask, dtype=np.uint8), n_cols)
    return [
        (key, row[:n_cols], diff)
        for (key, row, diff), keep in zip(deltas, mask.tolist())
        if keep
    ]


def materialize_columns(rows: list, needed: set[int]) -> dict[int, np.ndarray] | None:
    """Extract the needed columns as typed 1-D arrays; None if any column is
    not cleanly typed (None/Error/mixed/nested values).

    Uniform *Python* types are required — np.asarray would silently promote
    int/float mixes to float64 (precision loss above 2**53) and bool/int
    mixes to int64, changing values the row path preserves exactly.

    The native core does the scan+extract in one C pass per column when
    available; the Python loop below is the fallback and the semantics
    reference.
    """
    nm = _get_native_materialize()
    if nm is not None:
        res = nm(rows, tuple(needed), False)
        return None if res is None else _wrap_native_cols(res)
    cols: dict[int, np.ndarray] = {}
    for i in needed:
        vals = [r[i] for r in rows]
        t0 = type(vals[0])
        if t0 not in (bool, int, float, str):
            return None
        # C-level uniformity scan (set+map) — the per-value genexpr was the
        # single hottest line of the columnar path at 1M+ rows
        if set(map(type, vals)) != {t0}:
            return None
        try:
            arr = np.asarray(vals)
        except (ValueError, OverflowError, TypeError):
            return None
        if arr.ndim != 1 or arr.dtype.kind not in "bifU":
            return None
        if arr.dtype.kind == "i" and arr.size and int(arr.min()) == -(2**63):
            return None  # INT64_MIN: negation / // -1 would wrap
        cols[i] = arr
    return cols


_KIND_OK = {
    dt.INT: "bi",
    dt.FLOAT: "f",
    dt.BOOL: "b",
    dt.STR: "U",
}


def result_kind_ok(arr: np.ndarray, out_dtype) -> bool:
    """The vector result must already be in the declared dtype's kind —
    otherwise the per-row path's dt.coerce would alter values and we bail."""
    base = out_dtype.strip_optional() if hasattr(out_dtype, "strip_optional") else out_dtype
    allowed = _KIND_OK.get(base)
    if allowed is None:
        return True  # ANY etc. — whatever the math produced is the value
    return arr.dtype.kind in allowed
