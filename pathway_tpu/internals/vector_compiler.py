"""Columnar (vectorized) expression compilation for large epoch batches.

SURVEY.md §7.3: the host hot path should move columnar batches, not Python
row tuples.  The engine stays delta-correct and row-oriented at its edges;
inside an epoch, ``ExprNode``/``FilterNode``/``GroupByNode`` switch to a
numpy fast path when (a) the expression compiles to vector ops and (b) the
batch's columns materialize as typed 1-D arrays (no ``None``/``Error``
values, no mixed types).  Anything else falls back to the per-row
interpreter — semantics are identical by construction, because the fast
path *bails* (``VecBail``) rather than approximating:

* division/modulo with any zero divisor bails (per-row path yields ERROR
  for exactly the offending rows);
* ``**`` on ints bails (Python bignum semantics ≠ int64);
* columns containing None/Error/mixed types materialize as object arrays
  and bail.

Int arithmetic runs in int64 — the reference engine's own integer type
(``Value::Int`` is ``i64``, value.rs:210) — with overflow surfaced by
numpy where detectable.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import (
    CastExpression,
    CoalesceExpression,
    ColumnBinaryOpExpression,
    ColumnConstExpression,
    ColumnExpression,
    ColumnReference,
    ColumnUnaryOpExpression,
    ConvertExpression,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    UnwrapExpression,
)
from pathway_tpu.internals.thisclass import ThisPlaceholder

VEC_THRESHOLD = 64  # below this, per-row beats transpose + dispatch

# process-wide switch (benchmark baselines, debugging); the row path is the
# reference semantics, the vector path must be observationally identical
ENABLED = True


def set_enabled(flag: bool) -> None:
    global ENABLED
    ENABLED = bool(flag)

VecFn = Callable[[dict, int], np.ndarray]  # (columns by index, n) -> array


class VecBail(Exception):
    """Data-dependent condition the vector path cannot honor; caller falls
    back to the per-row interpreter for this batch."""


def _const_array(v, n: int) -> np.ndarray:
    return np.full(n, v)


def try_compile_vec(e: ColumnExpression, binder) -> tuple[VecFn, set[int]] | None:
    """Compile to a columnar evaluator, or None if not vectorizable.

    ``binder`` is the row binder (needs ``table``, ``col_index``).  Returns
    (fn, needed_column_indices).
    """
    needed: set[int] = set()
    fn = _compile(e, binder, needed)
    if fn is None:
        return None
    return fn, needed


def _compile(e, binder, needed: set[int]) -> VecFn | None:
    if isinstance(e, ColumnConstExpression):
        v = e._val
        if isinstance(v, (bool, int, float, str)):
            return lambda cols, n: _const_array(v, n)
        return None

    if isinstance(e, ColumnReference):
        tbl = e.table
        if not (isinstance(tbl, ThisPlaceholder) or tbl is binder.table):
            return None  # foreign/fetched columns use the row path
        if e.name == "id" or e.name not in binder.col_index:
            return None
        idx = binder.col_index[e.name]
        needed.add(idx)
        return lambda cols, n: cols[idx]

    if isinstance(e, ColumnBinaryOpExpression):
        lf = _compile(e._left, binder, needed)
        rf = _compile(e._right, binder, needed)
        if lf is None or rf is None:
            return None
        op = e._op
        return _bin_vec(op, lf, rf)

    if isinstance(e, ColumnUnaryOpExpression):
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        if e._op == "-":

            def neg(cols, n):
                v = f(cols, n)
                if v.dtype.kind not in "if":
                    raise VecBail
                return -v

            return neg
        if e._op == "~":

            def inv(cols, n):
                v = f(cols, n)
                if v.dtype.kind == "b":
                    return ~v
                if v.dtype.kind == "i":
                    return ~v
                raise VecBail

            return inv
        return None

    if isinstance(e, IfElseExpression):
        cf = _compile(e._if, binder, needed)
        tf = _compile(e._then, binder, needed)
        ff = _compile(e._else, binder, needed)
        if cf is None or tf is None or ff is None:
            return None

        def where(cols, n):
            c = cf(cols, n)
            if c.dtype.kind != "b":
                raise VecBail
            return np.where(c, tf(cols, n), ff(cols, n))

        return where

    if isinstance(e, IsNoneExpression):
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        # typed columns cannot hold None
        return lambda cols, n: np.zeros(n, bool)

    if isinstance(e, IsNotNoneExpression):
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        return lambda cols, n: np.ones(n, bool)

    if isinstance(e, CoalesceExpression):
        f = _compile(e._args[0], binder, needed)
        return f  # typed first arg is never None

    if isinstance(e, UnwrapExpression):
        return _compile(e._expr, binder, needed)

    if isinstance(e, CastExpression):  # Convert (from Json) stays row-wise
        f = _compile(e._expr, binder, needed)
        if f is None:
            return None
        target = e._return_type.strip_optional()
        if target is dt.INT:

            def to_int(cols, n):
                v = f(cols, n)
                if v.dtype.kind not in "bif":
                    raise VecBail
                return v.astype(np.int64)

            return to_int
        if target is dt.FLOAT:

            def to_float(cols, n):
                v = f(cols, n)
                if v.dtype.kind not in "bif":
                    raise VecBail
                return v.astype(np.float64)

            return to_float
        if target is dt.BOOL:

            def to_bool(cols, n):
                v = f(cols, n)
                if v.dtype.kind != "b":
                    raise VecBail
                return v

            return to_bool
        return None

    return None


_NUMERIC = "bif"

_I64_MAX = 2**63 - 1


def _abs_bound(arr: np.ndarray) -> int:
    """Largest |value| in an int array, computed safely in Python ints."""
    if arr.size == 0:
        return 0
    return max(abs(int(arr.max())), abs(int(arr.min())))


def _guard_int_overflow(op: str, lv: np.ndarray, rv: np.ndarray) -> None:
    """numpy int64 wraps silently; the row path uses Python bignums — any
    result that could exceed i64 must bail to the row interpreter."""
    if lv.dtype.kind != "i" and rv.dtype.kind != "i":
        return
    m1, m2 = _abs_bound(lv), _abs_bound(rv)
    if op in ("+", "-"):
        if m1 + m2 > _I64_MAX:
            raise VecBail
    elif op == "*":
        if m1 and m2 and m1 * m2 > _I64_MAX:
            raise VecBail


def _bin_vec(op: str, lf: VecFn, rf: VecFn) -> VecFn:
    def run(cols, n):
        lv = lf(cols, n)
        rv = rf(cols, n)
        lk, rk = lv.dtype.kind, rv.dtype.kind
        if op in ("==", "!="):
            if (lk == "U") != (rk == "U"):
                raise VecBail  # str vs non-str: row semantics return False/True
            res = lv == rv if op == "==" else lv != rv
            return res
        if op in ("<", "<=", ">", ">="):
            if lk == "U" and rk == "U":
                pass  # lexicographic, matches Python
            elif lk not in _NUMERIC or rk not in _NUMERIC:
                raise VecBail
            if op == "<":
                return lv < rv
            if op == "<=":
                return lv <= rv
            if op == ">":
                return lv > rv
            return lv >= rv
        if op in ("&", "|", "^"):
            if lk == "b" and rk == "b":
                return {"&": lv & rv, "|": lv | rv, "^": lv ^ rv}[op]
            if lk == "i" and rk == "i":
                return {"&": lv & rv, "|": lv | rv, "^": lv ^ rv}[op]
            raise VecBail
        if lk not in _NUMERIC or rk not in _NUMERIC:
            raise VecBail
        if op == "+":
            _guard_int_overflow(op, lv, rv)
            return lv + rv
        if op == "-":
            _guard_int_overflow(op, lv, rv)
            return lv - rv
        if op == "*":
            _guard_int_overflow(op, lv, rv)
            return lv * rv
        if op == "/":
            if np.any(rv == 0):
                raise VecBail  # per-row path poisons exactly those rows
            return lv / rv
        if op == "//":
            if np.any(rv == 0):
                raise VecBail
            return lv // rv
        if op == "%":
            if np.any(rv == 0):
                raise VecBail
            return lv % rv
        if op == "**":
            if lk in "bi" and rk in "bi":
                raise VecBail  # Python bignum semantics
            return lv**rv
        raise VecBail

    return run


def materialize_columns(rows: list, needed: set[int]) -> dict[int, np.ndarray] | None:
    """Extract the needed columns as typed 1-D arrays; None if any column is
    not cleanly typed (None/Error/mixed/nested values).

    Uniform *Python* types are required — np.asarray would silently promote
    int/float mixes to float64 (precision loss above 2**53) and bool/int
    mixes to int64, changing values the row path preserves exactly.
    """
    cols: dict[int, np.ndarray] = {}
    for i in needed:
        vals = [r[i] for r in rows]
        t0 = type(vals[0])
        if t0 not in (bool, int, float, str):
            return None
        # C-level uniformity scan (set+map) — the per-value genexpr was the
        # single hottest line of the columnar path at 1M+ rows
        if set(map(type, vals)) != {t0}:
            return None
        try:
            arr = np.asarray(vals)
        except (ValueError, OverflowError, TypeError):
            return None
        if arr.ndim != 1 or arr.dtype.kind not in "bifU":
            return None
        if arr.dtype.kind == "i" and arr.size and int(arr.min()) == -(2**63):
            return None  # INT64_MIN: negation / // -1 would wrap
        cols[i] = arr
    return cols


_KIND_OK = {
    dt.INT: "bi",
    dt.FLOAT: "f",
    dt.BOOL: "b",
    dt.STR: "U",
}


def result_kind_ok(arr: np.ndarray, out_dtype) -> bool:
    """The vector result must already be in the declared dtype's kind —
    otherwise the per-row path's dt.coerce would alter values and we bail."""
    base = out_dtype.strip_optional() if hasattr(out_dtype, "strip_optional") else out_dtype
    allowed = _KIND_OK.get(base)
    if allowed is None:
        return True  # ANY etc. — whatever the math produced is the value
    return arr.dtype.kind in allowed
