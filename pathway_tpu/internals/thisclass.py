"""``pw.this`` / ``pw.left`` / ``pw.right`` placeholders.

Parity target: ``/root/reference/python/pathway/internals/thisclass.py`` (313
LoC) + ``desugaring.py``.  A placeholder stands for a not-yet-known table;
attribute access produces unbound ``ColumnReference``s which get substituted
with the real table at the point of use (select/filter/join/reduce).
"""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnReference


class ThisPlaceholder:
    _kind: str

    def __init__(self, kind: str):
        object.__setattr__(self, "_kind", kind)

    def __repr__(self):
        return {"this": "pw.this", "left": "pw.left", "right": "pw.right"}.get(
            self._kind, f"pw.{self._kind}"
        )

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        return ColumnReference(self, name)

    def __getitem__(self, arg):
        if isinstance(arg, str):
            return ColumnReference(self, arg)
        if isinstance(arg, ColumnReference):
            return ColumnReference(self, arg.name)
        if isinstance(arg, (list, tuple)):
            return ThisSlice(self, keep=[_name_of(a) for a in arg])
        raise TypeError(f"cannot index pw.this with {type(arg)}")

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    def without(self, *columns) -> "ThisSlice":
        return ThisSlice(self, without=[_name_of(c) for c in columns])

    def ix(self, expression, *, optional: bool = False, context=None):
        # pw.this.ix(keys_expression) — row lookup by pointer column
        from pathway_tpu.internals.table import IxAppliedPlaceholder

        return IxAppliedPlaceholder(self, expression, optional=optional)

    def ix_ref(self, *args, optional: bool = False, context=None, instance=None):
        from pathway_tpu.internals.table import IxRefAppliedPlaceholder

        return IxRefAppliedPlaceholder(self, args, optional=optional, instance=instance)


def _name_of(c) -> str:
    if isinstance(c, str):
        return c
    if isinstance(c, ColumnReference):
        return c.name
    raise TypeError(f"expected column name or reference, got {type(c)}")


class ThisSlice:
    """``pw.this.without(x)`` / ``pw.this[["a","b"]]`` — expands in select(*args)."""

    def __init__(self, base, keep: list[str] | None = None, without: list[str] | None = None):
        self._base = base
        self._keep = keep
        self._without = without or []

    def _column_names(self, table) -> list[str]:
        names = self._keep if self._keep is not None else table.column_names()
        return [n for n in names if n not in self._without]

    def without(self, *columns) -> "ThisSlice":
        return ThisSlice(
            self._base, keep=self._keep, without=self._without + [_name_of(c) for c in columns]
        )

    def __getitem__(self, arg):
        if isinstance(arg, (list, tuple)):
            return ThisSlice(self._base, keep=[_name_of(a) for a in arg], without=self._without)
        return ColumnReference(self._base, _name_of(arg))

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ColumnReference(self._base, name)


this = ThisPlaceholder("this")
left = ThisPlaceholder("left")
right = ThisPlaceholder("right")
