"""Env-var-driven runtime configuration + the declared knob registry.

Parity target: ``/root/reference/python/pathway/internals/config.py`` (173
LoC) + engine-side ``src/engine/dataflow/config.rs:88-127``.  Same env
variables, same context-local override mechanism.

Every ``PATHWAY_*`` environment knob the package reads is DECLARED here
in :data:`ENV_KNOBS` — name, type, default, one-line doc, owning
subsystem — and read through the typed accessors (:func:`env_bool`,
:func:`env_int`, :func:`env_float`, :func:`env_str`, :func:`env_raw`).
``pathway_tpu lint`` enforces both halves: a direct ``os.environ`` read
of a ``PATHWAY_*`` name outside this module is an ``env-direct-read``
finding, and an undeclared name anywhere is ``env-undeclared``.
``docs/configuration.md`` is GENERATED from this registry
(:func:`render_env_docs`; regenerate with ``pathway_tpu lint
--update-config-docs``) and pinned in sync by the lint gate.

Accessors read ``os.environ`` live (no caching): worker processes get
their knobs from the spawning supervisor's environment, and tests
monkeypatch freely between runs.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from contextvars import ContextVar
from typing import Any

# ---------------------------------------------------------------------------
# The declared environment-knob registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EnvKnob:
    """One declared ``PATHWAY_*`` environment knob."""

    name: str
    kind: str  # "bool" | "int" | "float" | "str"
    default: Any
    doc: str
    subsystem: str


def _k(name: str, kind: str, default: Any, doc: str, subsystem: str) -> EnvKnob:
    return EnvKnob(name, kind, default, doc, subsystem)


ENV_KNOBS: tuple[EnvKnob, ...] = (
    # -- core runtime (this module) -----------------------------------------
    _k("PATHWAY_IGNORE_ASSERTS", "bool", False,
       "skip `pw.assert_*` runtime checks", "core"),
    _k("PATHWAY_RUNTIME_TYPECHECKING", "bool", False,
       "enable runtime schema/type checking of expressions", "core"),
    _k("PATHWAY_TERMINATE_ON_ERROR", "bool", True,
       "terminate the run on the first operator error (else poison rows "
       "and continue)", "core"),
    _k("PATHWAY_REPLAY_STORAGE", "str", None,
       "persistence root for record/replay runs (enables persistence "
       "without an explicit `pw.persistence.Config`)", "core"),
    _k("PATHWAY_SNAPSHOT_ACCESS", "str", None,
       "`record` | `replay` — connector snapshot mode for record/replay "
       "runs", "core"),
    _k("PATHWAY_PERSISTENCE_MODE", "str", None,
       "persistence/replay pacing mode (`batch` | `speedrun`)", "core"),
    _k("PATHWAY_REPLAY_MODE", "str", None,
       "legacy alias of PATHWAY_PERSISTENCE_MODE written by "
       "`pathway_tpu replay --mode`", "core"),
    _k("PATHWAY_CONTINUE_AFTER_REPLAY", "bool", False,
       "keep consuming live connector data after a recorded stream "
       "drains", "core"),
    _k("PATHWAY_LICENSE_KEY", "str", None,
       "license key for entitlement checks (internals/license.py)", "core"),
    _k("PATHWAY_MONITORING_SERVER", "str", None,
       "OTLP/HTTP collector endpoint for telemetry export (zero egress "
       "when unset)", "core"),
    _k("PATHWAY_THREADS", "int", 1,
       "worker threads per spawned process (accepted for parity; the "
       "device mesh is what scales compute)", "core"),
    _k("PATHWAY_PROCESSES", "int", 1,
       "SPMD cluster size: identical processes forming one TCP mesh",
       "core"),
    _k("PATHWAY_PROCESS_ID", "int", 0,
       "this worker's id within the cluster, in [0, PATHWAY_PROCESSES)",
       "core"),
    _k("PATHWAY_FIRST_PORT", "int", 10000,
       "base port of the worker TCP mesh (worker i listens on "
       "FIRST_PORT + i)", "core"),
    _k("PATHWAY_PEER_HOSTS", "str", None,
       "comma-separated hostname per worker id for multi-host meshes "
       "(unset = localhost mesh)", "core"),
    _k("PATHWAY_RUN_ID", "str", None,
       "cluster run id minted by `pathway_tpu spawn` (one per run, kept "
       "across supervised restarts)", "core"),
    _k("PATHWAY_MONITORING_HTTP_PORT", "int", None,
       "serve `GET /metrics` + the HTML dashboard on this port (worker i "
       "uses port + i)", "core"),
    # -- comm mesh (engine/comm.py) -----------------------------------------
    _k("PATHWAY_COMM_SECRET", "str", "",
       "shared mesh handshake secret (`spawn` mints one per run); empty "
       "disables authentication and pickled frame values", "comm"),
    _k("PATHWAY_COMM_MAX_FRAME_MB", "int", 256,
       "frame-size cap in MiB — a corrupt or hostile length field must "
       "not OOM the worker", "comm"),
    _k("PATHWAY_COMM_RECV_TIMEOUT_S", "float", 300.0,
       "deadline for `recv()` waiting on a tagged frame", "comm"),
    _k("PATHWAY_COMM_HEARTBEAT_S", "float", 2.0,
       "heartbeat send interval per link", "comm"),
    _k("PATHWAY_COMM_HEARTBEAT_TIMEOUT_S", "float", 30.0,
       "force-fail a link whose peer was silent (or stopped acking) for "
       "this long", "comm"),
    _k("PATHWAY_COMM_RECONNECT_WINDOW_S", "float", 15.0,
       "window a failed link may reconnect + resync before the peer is "
       "declared dead and its inbox purged", "comm"),
    _k("PATHWAY_COMM_SEND_DEADLINE_S", "float", None,
       "SO_SNDTIMEO deadline on any single blocking socket write "
       "(default: the heartbeat timeout; 0 disables)", "comm"),
    _k("PATHWAY_COMM_SEND_BUFFER_MB", "float", 64.0,
       "per-link retransmit buffer in MiB (unacked frames kept for "
       "reconnect resync)", "comm"),
    # -- fault injection (engine/faults.py) ---------------------------------
    _k("PATHWAY_FAULT_PLAN", "str", None,
       "seeded fault-injection plan (JSON) for chaos/soak runs", "faults"),
    _k("PATHWAY_RESTART_ATTEMPT", "int", 0,
       "supervisor restart attempt announced to workers (fault `attempt` "
       "filters key off it)", "faults"),
    # -- metrics / telemetry ------------------------------------------------
    _k("PATHWAY_METRICS_DISABLED", "bool", False,
       "kill switch: turn every metric update into an immediate return "
       "(the benchmark lever)", "metrics"),
    _k("PATHWAY_TELEMETRY_PROTOCOL", "str", "otlp-json",
       "telemetry wire format: `otlp-json` | `pathway-json` (legacy line "
       "JSON)", "metrics"),
    _k("PATHWAY_SERVICE_INSTANCE_ID", "str", None,
       "OTel `service.instance.id` resource attribute (default: random "
       "per process)", "metrics"),
    _k("PATHWAY_SERVICE_NAMESPACE", "str", "local-dev",
       "OTel `service.namespace` resource attribute", "metrics"),
    # -- request tracing & SLOs (engine/tracing.py, engine/slo.py) ----------
    _k("PATHWAY_TRACE_REQUESTS", "bool", True,
       "request-scoped distributed tracing of the serving path (ingress/"
       "admission/batcher/device/generation child spans, histogram "
       "exemplars, the `pathway_tpu requests` waterfall); `0` removes "
       "the per-request span layer entirely", "tracing"),
    _k("PATHWAY_TRACE_BUFFER", "int", 256,
       "finished request traces retained in the in-process ring the "
       "`pathway_tpu requests` CLI, `/status` and flight-recorder dumps "
       "read", "tracing"),
    _k("PATHWAY_SLOS", "str", None,
       "extra SLO declarations (semicolon-separated "
       "`name: metric pNN < threshold over window`, e.g. "
       "`latency: serve.latency.ms p95 < 250ms over 5m`) merged over "
       "the built-in registry; a redeclared name overrides it", "tracing"),
    # -- per-operator profiler / device accounting (engine/profiler.py) -----
    _k("PATHWAY_PROFILE", "bool", False,
       "enable the per-operator epoch profiler (top-N attribution "
       "snapshots exported as `profiler.operator.*`)", "profiler"),
    _k("PATHWAY_PROFILE_SAMPLE_EVERY", "int", 16,
       "profiler sampling cadence: aggregate operator totals every N "
       "processed epochs", "profiler"),
    _k("PATHWAY_PROFILE_TOP", "int", 20,
       "operators kept per profiler snapshot (bounds metric cardinality "
       "and the CLI render)", "profiler"),
    _k("PATHWAY_PROFILE_OUTPUT", "str", None,
       "write the run's final profiler snapshot to this JSON path "
       "(render it with `pathway_tpu profile <path>`)", "profiler"),
    _k("PATHWAY_PROFILE_JAX", "bool", True,
       "install jax.monitoring listeners counting compilations, jit "
       "cache misses and compile seconds (`jax.compile.*`, "
       "`jax.cache.miss`)", "profiler"),
    _k("PATHWAY_PROFILE_TRANSFERS", "bool", False,
       "wrap jax.device_put/device_get to count explicit host<->device "
       "transfer bytes (`jax.transfer.*`)", "profiler"),
    # -- data-plane freshness & backpressure (engine/freshness.py) ----------
    _k("PATHWAY_FRESHNESS", "bool", True,
       "track ingest-time freshness (per-output `freshness.e2e.ms` / "
       "`output.staleness.s`) and `backlog.*` backpressure gauges; `0` "
       "removes the per-epoch watermark pass entirely", "freshness"),
    _k("PATHWAY_STATUS_REFRESH_S", "float", 1.0,
       "default poll interval of the `pathway_tpu top` live view "
       "(`GET /status` on the monitoring HTTP server)", "freshness"),
    # -- benchmark harness (benchmarks/harness.py) --------------------------
    _k("PATHWAY_BENCH_BASELINE_DIR", "str", None,
       "directory of committed benchmark baselines (default: "
       "benchmarks/baselines/)", "bench"),
    _k("PATHWAY_BENCH_REPS", "int", None,
       "override the per-mode benchmark repetition count", "bench"),
    # -- persistence (engine/persistence.py) --------------------------------
    _k("PATHWAY_INCARNATION", "int", 0,
       "cluster incarnation lease this worker runs under (exported by "
       "the supervisor; fences zombie writers out of the root)",
       "persistence"),
    _k("PATHWAY_CHECKPOINT_GENERATIONS", "int", 3,
       "committed checkpoint generations retained (the deferred-GC "
       "fallback window)", "persistence"),
    _k("PATHWAY_CHECKPOINT_WRITERS", "int", 2,
       "background checkpoint writer threads; 0 = fully synchronous "
       "commits", "persistence"),
    _k("PATHWAY_CHECKPOINT_INFLIGHT_MB", "int", 256,
       "cap of in-flight snapshot bytes before commit staging "
       "backpressures the epoch thread", "persistence"),
    _k("PATHWAY_CHECKPOINT_PUBLISH_INTERVAL_MS", "float", 20.0,
       "minimum spacing between pipelined manifest publishes (staged "
       "frontiers conflate while the committer waits)", "persistence"),
    _k("PATHWAY_BLOB_RETRIES", "int", 3,
       "bounded retries for transient object-store errors", "persistence"),
    _k("PATHWAY_BLOB_RETRY_INITIAL_MS", "int", 200,
       "initial backoff of the blob retry schedule", "persistence"),
    _k("PATHWAY_PERSISTENT_STORAGE", "str", None,
       "filesystem root for the UDF DiskCache when no persistence config "
       "is active", "persistence"),
    # -- supervisor (engine/supervisor.py) ----------------------------------
    _k("PATHWAY_EPOCH_DEADLINE_S", "float", None,
       "hung-worker watchdog: no epoch progress for this long → SIGUSR1 "
       "(flight-recorder dump) → SIGTERM → SIGKILL into a supervised "
       "restart (unset or <= 0 disables)", "supervisor"),
    _k("PATHWAY_DEGRADED_SHRINK", "bool", False,
       "degraded-mode shrink (opt-in): when the same worker fails every "
       "attempt of a spent restart budget, rescale the supervised cluster "
       "to the surviving count instead of failing — checkpointed state "
       "re-partitions by shard range on resume", "supervisor"),
    _k("PATHWAY_STANDBY_COUNT", "int", 0,
       "warm-standby pool size (opt-in, `spawn --supervise --standbys`): "
       "K extra processes tail the persistence root so unplanned worker "
       "loss promotes a standby instead of restarting the group",
       "supervisor"),
    _k("PATHWAY_STANDBY_ID", "int", None,
       "exported by the supervisor into each standby process; its "
       "presence is what routes a spawned worker into standby-tailer "
       "mode instead of the event loop", "supervisor"),
    _k("PATHWAY_STANDBY_POLL_S", "float", 0.2,
       "standby tail cadence: how often a standby re-lists manifests, "
       "verifies newly committed generations, and refreshes its "
       "apply-cursor beacon", "supervisor"),
    _k("PATHWAY_STANDBY_PROMOTE_DEADLINE_S", "float", 20.0,
       "promotion deadline: if the standby + every survivor have not "
       "acked the PROMOTE request within this budget, the supervisor "
       "aborts the promotion and falls back to whole-group restart",
       "supervisor"),
    _k("PATHWAY_STANDBY_PROMOTIONS", "int", 8,
       "per-run promotion budget (separate from the restart budget): "
       "once spent, further worker deaths fall back to whole-group "
       "restart", "supervisor"),
    _k("PATHWAY_WORKER_FENCE", "int", 0,
       "per-worker fence token (exported by the supervisor to a promoted "
       "standby): commit-point writes carrying an older token than the "
       "lease's fence map are the dead worker's zombie and are rejected",
       "persistence"),
    # -- autoscaler (engine/autoscaler.py) ----------------------------------
    _k("PATHWAY_AUTOSCALE", "bool", False,
       "load-adaptive autoscaling (opt-in): the supervisor polls worker "
       "load beacons and grows/shrinks the cluster via live shard handoff "
       "under the PATHWAY_AUTOSCALE_* budgets below", "autoscaler"),
    _k("PATHWAY_AUTOSCALE_MIN_WORKERS", "int", 1,
       "shrink floor: the controller never targets fewer workers than "
       "this (and never below 1 regardless)", "autoscaler"),
    _k("PATHWAY_AUTOSCALE_MAX_WORKERS", "int", 8,
       "grow ceiling: the controller never targets more workers than "
       "this", "autoscaler"),
    _k("PATHWAY_AUTOSCALE_STALENESS_S", "float", 5.0,
       "grow trigger: worst per-worker output staleness above this for a "
       "full dwell window means the cluster is falling behind",
       "autoscaler"),
    _k("PATHWAY_AUTOSCALE_DWELL_S", "float", 10.0,
       "hysteresis dwell: the grow trigger must hold CONTINUOUSLY for "
       "this long before a rescale fires (one dip below threshold resets "
       "the clock) — oscillating load never flaps", "autoscaler"),
    _k("PATHWAY_AUTOSCALE_COOLDOWN_S", "float", 60.0,
       "post-rescale cooldown: no further scaling decision (either "
       "direction) for this long after a rescale fires", "autoscaler"),
    _k("PATHWAY_AUTOSCALE_IDLE_S", "float", 30.0,
       "shrink trigger: staleness comfortably low AND backlog ~empty "
       "continuously for this long shrinks the cluster one step",
       "autoscaler"),
    _k("PATHWAY_AUTOSCALE_BUDGET", "int", 4,
       "rescale budget: total grow/shrink decisions this supervisor run "
       "may fire; exhaustion logs loudly and pins the topology",
       "autoscaler"),
    _k("PATHWAY_AUTOSCALE_HANDOFF_DEADLINE_S", "float", 30.0,
       "live-handoff deadline: a posted handoff the workers have not "
       "fully acked within this window falls back to the restart-based "
       "rescale", "autoscaler"),
    # -- serving path (engine/serving.py, io/http/) -------------------------
    _k("PATHWAY_SERVE_ADMISSION", "bool", True,
       "`0` disables the serving admission controller entirely (every "
       "request is admitted immediately, no 429/queue/shedding — the "
       "unprotected mode `benchmarks/serving_overload.py` measures "
       "against)", "serving"),
    _k("PATHWAY_SERVE_DEADLINE_MS", "float", 30000.0,
       "default per-request deadline for REST queries (overridable per "
       "request via the `X-Pathway-Deadline-Ms` header); a request that "
       "cannot complete in budget is answered 504 and retracted before "
       "burning further work", "serving"),
    _k("PATHWAY_SERVE_INFLIGHT", "int", 64,
       "admission: max REST requests concurrently inside the pipeline "
       "(admitted, not yet answered); arrivals beyond it wait in the "
       "pending queue", "serving"),
    _k("PATHWAY_SERVE_INFLIGHT_MB", "float", 32.0,
       "admission: max summed request-body bytes in flight; the bytes "
       "axis of the same budget as `PATHWAY_SERVE_INFLIGHT`", "serving"),
    _k("PATHWAY_SERVE_QUEUE", "int", 128,
       "admission: max requests waiting for an in-flight slot; overflow "
       "is answered 429 + Retry-After immediately (shed newest, never a "
       "stranded socket)", "serving"),
    _k("PATHWAY_SERVE_QUEUE_DELAY_MS", "float", 250.0,
       "load shedding: CoDel-style target queue delay — admission waits "
       "(or output staleness) sustained above this arm the shedder",
       "serving"),
    _k("PATHWAY_SERVE_SHED_DWELL_S", "float", 1.0,
       "load shedding: queue delay must stay above target this long "
       "before degraded mode engages (any dip resets the clock — the "
       "`ScaleController` hysteresis shape)", "serving"),
    _k("PATHWAY_SERVE_RECOVER_S", "float", 5.0,
       "load shedding: queue delay must stay back under target this "
       "long before degraded mode disengages", "serving"),
    _k("PATHWAY_SERVE_DRAIN_S", "float", 10.0,
       "graceful drain budget: on shutdown/live-handoff the webserver "
       "stops accepting (503) and waits up to this long for in-flight "
       "requests to complete before the handoff fence proceeds",
       "serving"),
    # -- generation serving (pathway_tpu/serving/) --------------------------
    _k("PATHWAY_GENERATE_CONTINUOUS", "bool", True,
       "route `JaxChat` decoder generation through the continuous-"
       "batching scheduler (paged KV, per-step admission); `0` reverts "
       "to the static per-config `AsyncMicroBatcher` path "
       "(docs/generation_serving.md)", "generate"),
    _k("PATHWAY_GENERATE_SLOTS", "int", 8,
       "generation slot count — the fixed device batch width of the "
       "continuous decode step; finished rows free their slot every "
       "tick", "generate"),
    _k("PATHWAY_GENERATE_PAGE_SIZE", "int", 16,
       "tokens per KV page; KV memory is allocated and freed in pages, "
       "so footprint tracks live tokens instead of slots x max_cache",
       "generate"),
    _k("PATHWAY_GENERATE_PAGES", "int", 0,
       "KV pool size in pages (page 0 is the reserved null page); 0 "
       "auto-sizes to half the dense worst case, floored so one "
       "full-cache request always fits", "generate"),
    _k("PATHWAY_GENERATE_PREFILL_CHUNK", "int", 32,
       "prompt tokens prefilled per tick per slot — chunked prefill "
       "interleaves with decode so a long prompt cannot stall other "
       "requests' token cadence", "generate"),
    _k("PATHWAY_GENERATE_QUEUE", "int", 128,
       "max requests queued for a generation slot; overflow is "
       "answered 429 + Retry-After (page-pool exhaustion backpressures "
       "here, never an OOM)", "generate"),
    # -- device executor (pathway_tpu/device/) ------------------------------
    _k("PATHWAY_DEVICE_MAX_BATCH", "int", 512,
       "largest batch bucket of the DeviceExecutor's default bucketing "
       "policy (bigger batches split; smaller round up to powers of two)",
       "executor"),
    _k("PATHWAY_DEVICE_INFLIGHT_MB", "float", 256.0,
       "in-flight byte budget of the async device-dispatch queue; a full "
       "budget backpressures submitters (counted as "
       "`device.backpressure.s`)", "executor"),
    _k("PATHWAY_DEVICE_INFLIGHT_REQUESTS", "int", 64,
       "in-flight request budget of the async device-dispatch queue",
       "executor"),
    _k("PATHWAY_DEVICE_DONATE", "str", "auto",
       "donate padded input buffers to jitted device calls: `auto` "
       "(backends with donation support), `on`, `off`", "executor"),
    _k("PATHWAY_DEVICE_COST_ANALYSIS", "bool", True,
       "capture XLA cost_analysis/memory_analysis per compile-cache key "
       "(AOT compile path) feeding device.flops.total / "
       "device.utilization; `0` falls back to plain jit dispatch with "
       "uncosted accounting", "executor"),
    _k("PATHWAY_DEVICE_PEAK_FLOPS", "float", None,
       "per-device peak FLOP/s for the roofline utilization estimate "
       "(default: auto-detected from the device kind; the CPU rig gets "
       "a measured-peak default so the layer is testable without a TPU)",
       "executor"),
    _k("PATHWAY_DEVICE_TRACE_DIR", "str", None,
       "base directory for on-demand jax.profiler traces (`GET "
       "/trace?seconds=N` on the monitoring HTTP server, `pathway_tpu "
       "trace`); unset disables capture", "executor"),
    _k("PATHWAY_DEVICE_RESILIENCE", "bool", True,
       "device-path fault tolerance rail (typed failure classes, "
       "retries, OOM bucket ratchet, circuit breaker, quarantine); `0` "
       "reverts to raw PR-11 dispatch where any device error fails the "
       "caller", "executor"),
    _k("PATHWAY_DEVICE_RETRIES", "int", 2,
       "bounded retries for TRANSIENT device failures per dispatch "
       "(jittered exponential backoff, the shared udfs policy); compile "
       "failures and OOM are never retried at the same shape", "executor"),
    _k("PATHWAY_DEVICE_RETRY_DEADLINE_S", "float", 30.0,
       "wall-clock cap on one dispatch's whole retry affair — the retry "
       "loop must never outlast the freshness SLO it protects",
       "executor"),
    _k("PATHWAY_DEVICE_RETRY_BACKOFF_MS", "float", 50.0,
       "initial backoff before the first device retry (doubles per "
       "attempt, jittered by half the initial)", "executor"),
    _k("PATHWAY_DEVICE_BREAKER_THRESHOLD", "int", 5,
       "consecutive device failures (retries already spent) that trip a "
       "callable's circuit breaker OPEN — dispatches then route to the "
       "un-jitted host fallback (`device.breaker.state`, "
       "`device.fallback.*`)", "executor"),
    _k("PATHWAY_DEVICE_BREAKER_COOLDOWN_S", "float", 10.0,
       "open-breaker cooldown before one half-open probe is admitted "
       "back to the device (success closes, failure re-opens)",
       "executor"),
    _k("PATHWAY_DEVICE_DISPATCH_DEADLINE_S", "float", 0.0,
       "hard per-job dispatch deadline: a queued batch job running "
       "longer is failed with a typed hang error and the dispatch "
       "thread is respawned (`device.dispatch.restarts`); 0 disables "
       "hang escalation (long LLM-generation jobs use their own "
       "threads)", "executor"),
    _k("PATHWAY_DEVICE_QUARANTINE_KEEP", "int", 32,
       "poisoned-batch quarantine records retained per executor "
       "(newest kept; the total is still counted by "
       "`device.quarantine.batches`)", "executor"),
    # -- devices (parallel/mesh.py, internals/runner.py) --------------------
    _k("PATHWAY_JAX_DISTRIBUTED", "bool", False,
       "form a multi-host JAX device mesh too (`spawn "
       "--jax-distributed`): each process joins one global mesh",
       "devices"),
    _k("PATHWAY_DEVICE_COORDINATOR", "str", None,
       "host:port of the jax.distributed coordinator (default derived "
       "from worker 0's host and the mesh ports)", "devices"),
    # -- models / native kernels --------------------------------------------
    _k("PATHWAY_FUSED_ENCODER", "bool", True,
       "use the fused/packed encoder inference path", "models"),
    _k("PATHWAY_ENCODER_QUANTIZE", "str", None,
       "`int8` enables weight-only-quantized encoder inference", "models"),
    _k("PATHWAY_NATIVE", "bool", True,
       "`0` disables the native C++ kernels (numpy/python fallback)",
       "models"),
    _k("PATHWAY_COLUMNAR", "bool", True,
       "`0` forces every operator onto the row-wise reference evaluator "
       "(disables the columnar fast paths; see docs/columnar.md)",
       "models"),
    # -- CLI ----------------------------------------------------------------
    _k("PATHWAY_SPAWN_ARGS", "str", None,
       "arguments for `pathway_tpu spawn-from-env` (the k8s-operator "
       "hook)", "cli"),
)

ENV_REGISTRY: dict[str, EnvKnob] = {k.name: k for k in ENV_KNOBS}

_SUBSYSTEM_TITLES = (
    ("core", "Core runtime (`internals/config.py`)"),
    ("comm", "Worker mesh (`engine/comm.py`)"),
    ("faults", "Fault injection (`engine/faults.py`)"),
    ("metrics", "Metrics & telemetry (`engine/metrics.py`, `engine/telemetry.py`)"),
    ("tracing", "Request tracing & SLOs (`engine/tracing.py`, `engine/slo.py`)"),
    ("profiler", "Profiler & device accounting (`engine/profiler.py`)"),
    ("freshness", "Freshness & backpressure (`engine/freshness.py`)"),
    ("bench", "Benchmark harness (`benchmarks/harness.py`)"),
    ("persistence", "Persistence (`engine/persistence.py`)"),
    ("supervisor", "Supervisor (`engine/supervisor.py`)"),
    ("autoscaler", "Autoscaler (`engine/autoscaler.py`)"),
    ("serving", "Serving path (`engine/serving.py`, `io/http/`)"),
    ("generate", "Generation serving (`pathway_tpu/serving/`)"),
    ("executor", "Device executor (`pathway_tpu/device/`)"),
    ("devices", "Device mesh (`parallel/mesh.py`)"),
    ("models", "Models & native kernels"),
    ("cli", "CLI (`pathway_tpu/cli.py`)"),
)

_TRUTHY = ("1", "true", "yes", "on")
_FALSY = ("0", "false", "no", "off")


def _knob(name: str) -> EnvKnob:
    knob = ENV_REGISTRY.get(name)
    if knob is None:
        raise KeyError(
            f"{name} is not a declared environment knob — add it to "
            "internals/config.py:ENV_KNOBS (name, type, default, doc) and "
            "regenerate docs/configuration.md"
        )
    return knob


def env_raw(name: str) -> str | None:
    """The raw environment value of a DECLARED knob (None when unset).
    For knobs whose parse is deliberately custom (e.g. the watchdog
    deadline's positive-float-or-off semantics)."""
    _knob(name)
    return os.environ.get(name)


def env_str(name: str, default: Any = ...) -> Any:
    knob = _knob(name)
    raw = os.environ.get(name)
    if raw is None:
        return knob.default if default is ... else default
    return raw


def env_bool(name: str, default: Any = ...) -> bool:
    knob = _knob(name)
    fallback = knob.default if default is ... else default
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        # empty = unset (the `PATHWAY_NATIVE=` shell idiom keeps the
        # default), matching env_int/env_float — NOT falsy
        return bool(fallback)
    v = raw.strip().lower()
    if v in _TRUTHY:
        return True
    if v in _FALSY:
        return False
    return bool(fallback)


def env_int(name: str, default: Any = ...) -> Any:
    knob = _knob(name)
    fallback = knob.default if default is ... else default
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return fallback
    try:
        return int(raw)
    except ValueError:
        return fallback


def env_float(name: str, default: Any = ...) -> Any:
    knob = _knob(name)
    fallback = knob.default if default is ... else default
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return fallback
    try:
        return float(raw)
    except ValueError:
        return fallback


def render_env_docs() -> str:
    """``docs/configuration.md``, generated.  The lint gate pins the file
    byte-identical to this render (rule ``env-docs-stale``)."""
    lines = [
        "# Configuration knobs",
        "",
        "<!-- GENERATED FILE — do not edit. -->",
        "<!-- Source: pathway_tpu/internals/config.py:ENV_KNOBS. -->",
        "<!-- Regenerate: pathway_tpu lint --update-config-docs -->",
        "",
        "Every `PATHWAY_*` environment variable the runtime reads, in one",
        "declared registry (`internals/config.py:ENV_KNOBS`).  Code reads",
        "these through typed accessors (`config.env_bool` / `env_int` /",
        "`env_float` / `env_str` / `env_raw`); `pathway_tpu lint` rejects",
        "direct `os.environ` reads (`env-direct-read`) and undeclared",
        "names (`env-undeclared`), so this page is complete by",
        "construction.",
        "",
    ]
    for key, title in _SUBSYSTEM_TITLES:
        knobs = [k for k in ENV_KNOBS if k.subsystem == key]
        if not knobs:
            continue
        lines.append(f"## {title}")
        lines.append("")
        lines.append("| Variable | Type | Default | Meaning |")
        lines.append("|---|---|---|---|")
        for k in knobs:
            default = "—" if k.default is None else repr(k.default)
            lines.append(
                f"| `{k.name}` | {k.kind} | `{default}` | {k.doc} |"
            )
        lines.append("")
    return "\n".join(lines)


def _env_bool(name: str, default: bool = False) -> bool:
    return env_bool(name, default)


def _env_int(name: str, default: int) -> int:
    return env_int(name, default)


@dataclasses.dataclass
class PathwayConfig:
    # mirrors PathwayConfig (internals/config.py:57-97)
    ignore_asserts: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_IGNORE_ASSERTS")
    )
    runtime_typechecking: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_RUNTIME_TYPECHECKING")
    )
    terminate_on_error: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_TERMINATE_ON_ERROR", True)
    )
    replay_storage: str | None = dataclasses.field(
        default_factory=lambda: env_str("PATHWAY_REPLAY_STORAGE")
    )
    snapshot_access: str | None = dataclasses.field(
        default_factory=lambda: env_str("PATHWAY_SNAPSHOT_ACCESS")
    )
    persistence_mode: str | None = dataclasses.field(
        default_factory=lambda: env_str("PATHWAY_PERSISTENCE_MODE")
    )
    continue_after_replay: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_CONTINUE_AFTER_REPLAY")
    )
    license_key: str | None = dataclasses.field(
        default_factory=lambda: env_str("PATHWAY_LICENSE_KEY")
    )
    monitoring_server: str | None = dataclasses.field(
        default_factory=lambda: env_str("PATHWAY_MONITORING_SERVER")
    )
    # worker topology (config.rs:88-120)
    threads: int = dataclasses.field(default_factory=lambda: _env_int("PATHWAY_THREADS", 1))
    processes: int = dataclasses.field(default_factory=lambda: _env_int("PATHWAY_PROCESSES", 1))
    process_id: int = dataclasses.field(default_factory=lambda: _env_int("PATHWAY_PROCESS_ID", 0))
    first_port: int = dataclasses.field(
        default_factory=lambda: _env_int("PATHWAY_FIRST_PORT", 10000)
    )
    # multi-host clusters: comma-separated hostname per worker id
    # (PATHWAY_PEER_HOSTS=pod-0.svc,pod-1.svc,...); empty = localhost mesh
    peer_hosts: list | None = dataclasses.field(
        default_factory=lambda: (
            [h.strip() for h in env_str("PATHWAY_PEER_HOSTS", "").split(",")]
            if env_str("PATHWAY_PEER_HOSTS")
            else None
        )
    )
    run_id: str | None = dataclasses.field(
        default_factory=lambda: env_str("PATHWAY_RUN_ID")
    )
    monitoring_http_port: int | None = dataclasses.field(
        default_factory=lambda: env_int("PATHWAY_MONITORING_HTTP_PORT")
    )

    @property
    def worker_count(self) -> int:
        return self.threads * self.processes


_config_var: ContextVar[PathwayConfig | None] = ContextVar("pathway_config", default=None)
_global_config: PathwayConfig | None = None


def get_config() -> PathwayConfig:
    cfg = _config_var.get()
    if cfg is not None:
        return cfg
    global _global_config
    if _global_config is None:
        _global_config = PathwayConfig()
    return _global_config


def refresh_config() -> None:
    global _global_config
    _global_config = PathwayConfig()


@contextlib.contextmanager
def local_pathway_config(**overrides: Any):
    base = get_config()
    cfg = dataclasses.replace(base, **overrides)
    token = _config_var.set(cfg)
    try:
        yield cfg
    finally:
        _config_var.reset(token)


def set_license_key(key: str | None) -> None:
    get_config().license_key = key


def set_monitoring_config(*, server_endpoint: str | None = None) -> None:
    get_config().monitoring_server = server_endpoint


def pathway_config() -> PathwayConfig:
    return get_config()
