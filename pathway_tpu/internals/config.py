"""Env-var-driven runtime configuration.

Parity target: ``/root/reference/python/pathway/internals/config.py`` (173
LoC) + engine-side ``src/engine/dataflow/config.rs:88-127``.  Same env
variables, same context-local override mechanism.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from contextvars import ContextVar
from typing import Any


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        return default


@dataclasses.dataclass
class PathwayConfig:
    # mirrors PathwayConfig (internals/config.py:57-97)
    ignore_asserts: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_IGNORE_ASSERTS")
    )
    runtime_typechecking: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_RUNTIME_TYPECHECKING")
    )
    terminate_on_error: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_TERMINATE_ON_ERROR", True)
    )
    replay_storage: str | None = dataclasses.field(
        default_factory=lambda: os.environ.get("PATHWAY_REPLAY_STORAGE")
    )
    snapshot_access: str | None = dataclasses.field(
        default_factory=lambda: os.environ.get("PATHWAY_SNAPSHOT_ACCESS")
    )
    persistence_mode: str | None = dataclasses.field(
        default_factory=lambda: os.environ.get("PATHWAY_PERSISTENCE_MODE")
    )
    continue_after_replay: bool = dataclasses.field(
        default_factory=lambda: _env_bool("PATHWAY_CONTINUE_AFTER_REPLAY")
    )
    license_key: str | None = dataclasses.field(
        default_factory=lambda: os.environ.get("PATHWAY_LICENSE_KEY")
    )
    monitoring_server: str | None = dataclasses.field(
        default_factory=lambda: os.environ.get("PATHWAY_MONITORING_SERVER")
    )
    # worker topology (config.rs:88-120)
    threads: int = dataclasses.field(default_factory=lambda: _env_int("PATHWAY_THREADS", 1))
    processes: int = dataclasses.field(default_factory=lambda: _env_int("PATHWAY_PROCESSES", 1))
    process_id: int = dataclasses.field(default_factory=lambda: _env_int("PATHWAY_PROCESS_ID", 0))
    first_port: int = dataclasses.field(
        default_factory=lambda: _env_int("PATHWAY_FIRST_PORT", 10000)
    )
    # multi-host clusters: comma-separated hostname per worker id
    # (PATHWAY_PEER_HOSTS=pod-0.svc,pod-1.svc,...); empty = localhost mesh
    peer_hosts: list | None = dataclasses.field(
        default_factory=lambda: (
            [h.strip() for h in os.environ["PATHWAY_PEER_HOSTS"].split(",")]
            if os.environ.get("PATHWAY_PEER_HOSTS")
            else None
        )
    )
    run_id: str | None = dataclasses.field(default_factory=lambda: os.environ.get("PATHWAY_RUN_ID"))
    monitoring_http_port: int | None = dataclasses.field(
        default_factory=lambda: (
            int(os.environ["PATHWAY_MONITORING_HTTP_PORT"])
            if "PATHWAY_MONITORING_HTTP_PORT" in os.environ
            else None
        )
    )

    @property
    def worker_count(self) -> int:
        return self.threads * self.processes


_config_var: ContextVar[PathwayConfig | None] = ContextVar("pathway_config", default=None)
_global_config: PathwayConfig | None = None


def get_config() -> PathwayConfig:
    cfg = _config_var.get()
    if cfg is not None:
        return cfg
    global _global_config
    if _global_config is None:
        _global_config = PathwayConfig()
    return _global_config


def refresh_config() -> None:
    global _global_config
    _global_config = PathwayConfig()


@contextlib.contextmanager
def local_pathway_config(**overrides: Any):
    base = get_config()
    cfg = dataclasses.replace(base, **overrides)
    token = _config_var.set(cfg)
    try:
        yield cfg
    finally:
        _config_var.reset(token)


def set_license_key(key: str | None) -> None:
    get_config().license_key = key


def set_monitoring_config(*, server_endpoint: str | None = None) -> None:
    get_config().monitoring_server = server_endpoint


def pathway_config() -> PathwayConfig:
    return get_config()
