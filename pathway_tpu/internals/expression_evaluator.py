"""Compile column-expression ASTs to per-row callables.

Parity target: ``/root/reference/python/pathway/internals/graph_runner/
expression_evaluator.py`` (1,124 LoC) + the engine-side ``Expression``
interpreter (``src/engine/expression.rs``).  The reference lowers every
expression into a Rust expression tree evaluated per batch; here we compile
to a Python closure evaluated per row, with the same semantics:

* ``Value::Error`` poisoning — any Error operand makes the result Error
  (error.rs / dataflow.rs:582-673).
* None propagation in arithmetic/comparisons mirrors the reference's
  optional-type rules (operands must be unwrapped; at runtime None yields
  None rather than raising, matching pathway's lenient runtime path).
"""

from __future__ import annotations

import math
from typing import Any, Callable

from pathway_tpu.engine.types import ERROR, Error, Json, Pointer, hash_values
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    CastExpression,
    CoalesceExpression,
    ColumnBinaryOpExpression,
    ColumnConstExpression,
    ColumnExpression,
    ColumnReference,
    ColumnUnaryOpExpression,
    ConvertExpression,
    DeclareTypeExpression,
    FillErrorExpression,
    IfElseExpression,
    IsNoneExpression,
    IsNotNoneExpression,
    MakeTupleExpression,
    MethodCallExpression,
    PointerExpression,
    ReducerExpression,
    RequireExpression,
    SequenceGetExpression,
    UnwrapExpression,
)
from pathway_tpu.internals import dtype as dt

RowFn = Callable[[int, tuple], Any]


class EvalError(Exception):
    pass


def _is_err(v) -> bool:
    return isinstance(v, Error)


_CMP_NONE_OK = {"==", "!="}


def _binop(op: str, lv, rv):
    if _is_err(lv) or _is_err(rv):
        return ERROR
    if op == "==":
        return lv == rv
    if op == "!=":
        return lv != rv
    if lv is None or rv is None:
        if op in ("&", "|"):
            pass  # fall through: bool ops on None are errors below
        return None
    try:
        if op == "+":
            if isinstance(lv, Json) or isinstance(rv, Json):
                return ERROR
            return lv + rv
        if op == "-":
            return lv - rv
        if op == "*":
            return lv * rv
        if op == "/":
            if isinstance(lv, int) and isinstance(rv, int):
                if rv == 0:
                    return ERROR
                return lv / rv
            if isinstance(rv, (int, float)) and rv == 0:
                return ERROR
            return lv / rv
        if op == "//":
            if rv == 0:
                return ERROR
            return lv // rv
        if op == "%":
            if isinstance(rv, (int, float)) and rv == 0:
                return ERROR
            return lv % rv
        if op == "**":
            return lv**rv
        if op == "<":
            return lv < rv
        if op == "<=":
            return lv <= rv
        if op == ">":
            return lv > rv
        if op == ">=":
            return lv >= rv
        if op == "&":
            return lv & rv
        if op == "|":
            return lv | rv
        if op == "^":
            return lv ^ rv
        if op == "@":
            return lv @ rv
    except (TypeError, ValueError, ZeroDivisionError, OverflowError):
        return ERROR
    raise EvalError(f"unknown operator {op}")


class Binder:
    """Resolves ColumnReferences to accessors for a given evaluation site."""

    def resolve(self, ref: ColumnReference) -> RowFn:
        raise NotImplementedError

    def resolve_dtype(self, ref: ColumnReference) -> dt.DType:
        return dt.ANY


def compile_expr(e: ColumnExpression, binder: Binder) -> RowFn:
    if isinstance(e, ColumnConstExpression):
        v = e._val
        return lambda key, row: v

    if isinstance(e, ColumnReference):
        return binder.resolve(e)

    if isinstance(e, ColumnBinaryOpExpression):
        lf = compile_expr(e._left, binder)
        rf = compile_expr(e._right, binder)
        op = e._op
        return lambda key, row: _binop(op, lf(key, row), rf(key, row))

    if isinstance(e, ColumnUnaryOpExpression):
        f = compile_expr(e._expr, binder)
        if e._op == "-":

            def neg(key, row):
                v = f(key, row)
                if v is None or _is_err(v):
                    return v
                try:
                    return -v
                except TypeError:
                    return ERROR

            return neg
        if e._op == "~":

            def inv(key, row):
                v = f(key, row)
                if v is None or _is_err(v):
                    return v
                if isinstance(v, bool):
                    return not v
                return ~v

            return inv
        raise EvalError(f"unknown unary {e._op}")

    if isinstance(e, AsyncApplyExpression):
        # compiled specially by the table layer (AsyncApplyNode); when reached
        # here (e.g. nested), run the coroutine synchronously.
        fns = [compile_expr(a, binder) for a in e._args]
        kfns = {k: compile_expr(v, binder) for k, v in e._kwargs.items()}
        fun = e._fun

        def apply_async_sync(key, row):
            import asyncio

            args = [f(key, row) for f in fns]
            kwargs = {k: f(key, row) for k, f in kfns.items()}
            if any(_is_err(a) for a in args) or any(_is_err(v) for v in kwargs.values()):
                return ERROR
            try:
                return asyncio.run(fun(*args, **kwargs))
            except Exception:
                return ERROR

        return apply_async_sync

    if isinstance(e, ApplyExpression):
        fns = [compile_expr(a, binder) for a in e._args]
        kfns = {k: compile_expr(v, binder) for k, v in e._kwargs.items()}
        fun = e._fun
        propagate_none = e._propagate_none
        err_cls = Error

        if not kfns and len(fns) == 1:
            # the dominant shape (one positional arg, no kwargs): no list
            # build, no generator-based error scans — this wrapper runs
            # once per row on every Apply in a pipeline
            f0 = fns[0]

            def apply_fn1(key, row):
                a = f0(key, row)
                if isinstance(a, err_cls):
                    return ERROR
                if propagate_none and a is None:
                    return None
                try:
                    return fun(a)
                except Exception:
                    from pathway_tpu.internals import config as _cfg

                    if _cfg.get_config().terminate_on_error:
                        raise
                    return ERROR

            return apply_fn1

        if not kfns:

            def apply_fn_pos(key, row):
                args = [f(key, row) for f in fns]
                for a in args:
                    if isinstance(a, err_cls):
                        return ERROR
                if propagate_none:
                    for a in args:
                        if a is None:
                            return None
                try:
                    return fun(*args)
                except Exception:
                    from pathway_tpu.internals import config as _cfg

                    if _cfg.get_config().terminate_on_error:
                        raise
                    return ERROR

            return apply_fn_pos

        def apply_fn(key, row):
            args = [f(key, row) for f in fns]
            kwargs = {k: f(key, row) for k, f in kfns.items()}
            if any(_is_err(a) for a in args) or any(_is_err(v) for v in kwargs.values()):
                return ERROR
            if propagate_none and any(a is None for a in args):
                return None
            try:
                return fun(*args, **kwargs)
            except Exception as exc:
                from pathway_tpu.internals import config as _cfg

                if _cfg.get_config().terminate_on_error:
                    raise
                return ERROR

        return apply_fn

    if isinstance(e, CastExpression):
        f = compile_expr(e._expr, binder)
        target = e._return_type.strip_optional()

        def cast_fn(key, row):
            v = f(key, row)
            if v is None or _is_err(v):
                return v
            try:
                if target is dt.INT:
                    return int(v)
                if target is dt.FLOAT:
                    return float(v)
                if target is dt.BOOL:
                    return bool(v)
                if target is dt.STR:
                    return str(v)
                return v
            except (TypeError, ValueError):
                return ERROR

        return cast_fn

    if isinstance(e, ConvertExpression):
        f = compile_expr(e._expr, binder)
        target = e._return_type.strip_optional()
        unwrap_flag = e._unwrap

        def convert_fn(key, row):
            v = f(key, row)
            if _is_err(v):
                return v
            if isinstance(v, Json):
                v = v.value
            if v is None:
                if unwrap_flag:
                    return ERROR
                return None
            try:
                if target is dt.INT:
                    if isinstance(v, bool):
                        return int(v)
                    if isinstance(v, (int, float)):
                        if isinstance(v, float) and v != int(v):
                            return ERROR
                        return int(v)
                    return ERROR
                if target is dt.FLOAT:
                    if isinstance(v, bool):
                        return float(v)
                    if isinstance(v, (int, float)):
                        return float(v)
                    return ERROR
                if target is dt.BOOL:
                    return v if isinstance(v, bool) else ERROR
                if target is dt.STR:
                    return v if isinstance(v, str) else ERROR
            except (TypeError, ValueError):
                return ERROR
            return ERROR

        return convert_fn

    if isinstance(e, DeclareTypeExpression):
        return compile_expr(e._expr, binder)

    if isinstance(e, CoalesceExpression):
        fns = [compile_expr(a, binder) for a in e._args]

        def coalesce_fn(key, row):
            for f in fns:
                v = f(key, row)
                if _is_err(v):
                    return v
                if v is not None:
                    return v
            return None

        return coalesce_fn

    if isinstance(e, RequireExpression):
        vf = compile_expr(e._val, binder)
        fns = [compile_expr(a, binder) for a in e._args]

        def require_fn(key, row):
            for f in fns:
                v = f(key, row)
                if _is_err(v):
                    return v
                if v is None:
                    return None
            return vf(key, row)

        return require_fn

    if isinstance(e, IfElseExpression):
        cf = compile_expr(e._if, binder)
        tf = compile_expr(e._then, binder)
        ef = compile_expr(e._else, binder)

        def if_else_fn(key, row):
            c = cf(key, row)
            if _is_err(c):
                return c
            if c is None:
                return None
            return tf(key, row) if c else ef(key, row)

        return if_else_fn

    if isinstance(e, IsNotNoneExpression):
        f = compile_expr(e._expr, binder)
        return lambda key, row: (
            ERROR if _is_err(v := f(key, row)) else v is not None
        )

    if isinstance(e, IsNoneExpression):
        f = compile_expr(e._expr, binder)
        return lambda key, row: (
            ERROR if _is_err(v := f(key, row)) else v is None
        )

    if isinstance(e, MakeTupleExpression):
        fns = [compile_expr(a, binder) for a in e._args]

        def make_tuple_fn(key, row):
            vals = tuple(f(key, row) for f in fns)
            if any(_is_err(v) for v in vals):
                return ERROR
            return vals

        return make_tuple_fn

    if isinstance(e, SequenceGetExpression):
        objf = compile_expr(e._obj, binder)
        idxf = compile_expr(e._index, binder)
        deff = compile_expr(e._default, binder)
        checked = e._check_if_exists

        def get_fn(key, row):
            obj = objf(key, row)
            idx = idxf(key, row)
            if _is_err(obj) or _is_err(idx):
                return ERROR
            if obj is None:
                return None
            try:
                if isinstance(obj, Json):
                    inner = obj.value
                    if isinstance(inner, dict):
                        if checked:
                            if idx in inner:
                                return Json(inner[idx])
                            return deff(key, row)
                        return Json(inner[idx])
                    if isinstance(inner, (list, str)):
                        if checked:
                            if isinstance(idx, int) and -len(inner) <= idx < len(inner):
                                return Json(inner[idx])
                            return deff(key, row)
                        return Json(inner[idx])
                    if checked:
                        return deff(key, row)
                    return ERROR
                return obj[idx]
            except (KeyError, IndexError, TypeError):
                if checked:
                    return deff(key, row)
                return ERROR

        return get_fn

    if isinstance(e, MethodCallExpression):
        fns = [compile_expr(a, binder) for a in e._args]
        kfns = {k: compile_expr(v, binder) for k, v in e._kwargs.items()}
        fun = e._fun
        propagate_none = e._propagate_none

        def method_fn(key, row):
            args = [f(key, row) for f in fns]
            if any(_is_err(a) for a in args):
                return ERROR
            if propagate_none and args and args[0] is None:
                return None
            kwargs = {k: f(key, row) for k, f in kfns.items()}
            try:
                return fun(*args, **kwargs)
            except Exception:
                return ERROR

        return method_fn

    if isinstance(e, UnwrapExpression):
        f = compile_expr(e._expr, binder)

        def unwrap_fn(key, row):
            v = f(key, row)
            if v is None:
                return ERROR
            return v

        return unwrap_fn

    if isinstance(e, FillErrorExpression):
        f = compile_expr(e._expr, binder)
        rf = compile_expr(e._replacement, binder)

        def fill_error_fn(key, row):
            v = f(key, row)
            if _is_err(v):
                return rf(key, row)
            return v

        return fill_error_fn

    if isinstance(e, PointerExpression):
        fns = [compile_expr(a, binder) for a in e._args]
        optional = e._optional
        instance_f = (
            compile_expr(expr_mod._wrap(e._instance), binder)
            if e._instance is not None
            else None
        )

        def pointer_fn(key, row):
            vals = [f(key, row) for f in fns]
            if any(_is_err(v) for v in vals):
                return ERROR
            if optional and any(v is None for v in vals):
                return None
            if instance_f is not None:
                vals.append(instance_f(key, row))
            return Pointer(hash_values(vals))

        return pointer_fn

    if isinstance(e, ReducerExpression):
        raise EvalError(
            "reducer expression used outside reduce(): "
            f"{e!r} — reducers are only valid inside groupby(...).reduce(...)"
        )

    raise EvalError(f"cannot compile expression {e!r} of type {type(e)}")
