"""Pure-Python Ed25519 (RFC 8032) — fallback for offline license files.

The license layer (``internals/license.py``) verifies ed25519-signed
offline license files.  The reference build links a Rust ed25519 crate;
here the preferred implementation is the ``cryptography`` wheel, but the
container this framework targets may not ship it — and a missing
*optional* dependency must degrade to a slower implementation, not to
``ModuleNotFoundError`` at import time.

This is the RFC 8032 reference construction with extended homogeneous
coordinates (the complete addition formula of §5.1.4), so verification
costs two scalar multiplications at a few tens of milliseconds — entirely
acceptable for the handful of license checks a process performs, and
deterministic signing means signatures are byte-identical to the
``cryptography`` wheel's.

NOT constant-time: fine for license *verification* against a public key,
and for the test-fixture signer; do not reuse for online protocols
handling attacker-timed secret keys.
"""

from __future__ import annotations

import hashlib

__all__ = ["publickey", "sign", "verify"]

_p = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493


def _inv(x: int) -> int:
    return pow(x, _p - 2, _p)


_d = (-121665 * _inv(121666)) % _p
_SQRT_M1 = pow(2, (_p - 1) // 4, _p)  # sqrt(-1) mod p

# base point B: y = 4/5, x recovered even
_g_y = (4 * _inv(5)) % _p


def _recover_x(y: int, sign: int) -> int | None:
    if y >= _p:
        return None
    x2 = (y * y - 1) * _inv(_d * y * y + 1) % _p
    if x2 == 0:
        return None if sign else 0
    x = pow(x2, (_p + 3) // 8, _p)
    if (x * x - x2) % _p != 0:
        x = x * _SQRT_M1 % _p
    if (x * x - x2) % _p != 0:
        return None
    if (x & 1) != sign:
        x = _p - x
    return x


_g_x = _recover_x(_g_y, 0)
# extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z
_G = (_g_x, _g_y, 1, _g_x * _g_y % _p)
_IDENT = (0, 1, 1, 0)


def _add(P: tuple, Q: tuple) -> tuple:
    """Complete twisted-Edwards addition (RFC 8032 §5.1.4)."""
    x1, y1, z1, t1 = P
    x2, y2, z2, t2 = Q
    a = (y1 - x1) * (y2 - x2) % _p
    b = (y1 + x1) * (y2 + x2) % _p
    c = 2 * t1 * t2 * _d % _p
    dd = 2 * z1 * z2 % _p
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % _p, g * h % _p, f * g % _p, e * h % _p)


def _mul(s: int, P: tuple) -> tuple:
    Q = _IDENT
    while s > 0:
        if s & 1:
            Q = _add(Q, P)
        P = _add(P, P)
        s >>= 1
    return Q


def _compress(P: tuple) -> bytes:
    x, y, z, _t = P
    zinv = _inv(z)
    x, y = x * zinv % _p, y * zinv % _p
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(data: bytes) -> tuple | None:
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % _p)


def _equal(P: tuple, Q: tuple) -> bool:
    x1, y1, z1, _ = P
    x2, y2, z2, _ = Q
    return (x1 * z2 - x2 * z1) % _p == 0 and (y1 * z2 - y2 * z1) % _p == 0


def _sha512_modq(data: bytes) -> int:
    return int.from_bytes(hashlib.sha512(data).digest(), "little") % _L


def _expand(secret: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(secret).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def publickey(secret: bytes) -> bytes:
    """32-byte public key of a 32-byte seed."""
    if len(secret) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    a, _prefix = _expand(secret)
    return _compress(_mul(a, _G))


def sign(secret: bytes, message: bytes) -> bytes:
    """Deterministic RFC 8032 signature (64 bytes) over ``message``."""
    a, prefix = _expand(secret)
    A = _compress(_mul(a, _G))
    r = _sha512_modq(prefix + message)
    Rs = _compress(_mul(r, _G))
    k = _sha512_modq(Rs + A + message)
    s = (r + k * a) % _L
    return Rs + int.to_bytes(s, 32, "little")


def verify(public: bytes, signature: bytes, message: bytes) -> bool:
    """True iff ``signature`` is a valid signature of ``message``."""
    if len(public) != 32 or len(signature) != 64:
        return False
    A = _decompress(public)
    R = _decompress(signature[:32])
    if A is None or R is None:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= _L:
        return False
    k = _sha512_modq(signature[:32] + public + message)
    return _equal(_mul(s, _G), _add(R, _mul(k, A)))
