"""Column expression DSL.

Parity target: ``/root/reference/python/pathway/internals/expression.py``
(1,179 LoC) plus the ``expressions/{date_time,numerical,string}.py`` method
namespaces.  Expressions are passive ASTs; the engine's expression evaluator
compiles them to per-row callables (and, for device-bound columns, to jax
functions).  Operator overloading, ``pw.this`` desugaring, None- and
Error-propagation semantics follow the reference.
"""

from __future__ import annotations

import datetime
from typing import Any, Callable, Iterable

from pathway_tpu.engine.types import ERROR, Error, Json, Pointer
from pathway_tpu.internals import dtype as dt


class ColumnExpression:
    """Base class of all column expressions."""

    _dtype_hint: dt.DType | None = None

    def __call__(self, *args, **kwargs):
        """Invoke a column of callables (row-transformer ``@method`` columns;
        the reference lowers this via ``method_call_transformer``,
        row_transformer.py:80)."""
        return ApplyExpression(
            lambda f, *a, **kw: f(*a, **kw), None, self, *args, **kwargs
        )

    # -- arithmetic --
    def __add__(self, other):
        return ColumnBinaryOpExpression("+", self, other)

    def __radd__(self, other):
        return ColumnBinaryOpExpression("+", other, self)

    def __sub__(self, other):
        return ColumnBinaryOpExpression("-", self, other)

    def __rsub__(self, other):
        return ColumnBinaryOpExpression("-", other, self)

    def __mul__(self, other):
        return ColumnBinaryOpExpression("*", self, other)

    def __rmul__(self, other):
        return ColumnBinaryOpExpression("*", other, self)

    def __truediv__(self, other):
        return ColumnBinaryOpExpression("/", self, other)

    def __rtruediv__(self, other):
        return ColumnBinaryOpExpression("/", other, self)

    def __floordiv__(self, other):
        return ColumnBinaryOpExpression("//", self, other)

    def __rfloordiv__(self, other):
        return ColumnBinaryOpExpression("//", other, self)

    def __mod__(self, other):
        return ColumnBinaryOpExpression("%", self, other)

    def __rmod__(self, other):
        return ColumnBinaryOpExpression("%", other, self)

    def __pow__(self, other):
        return ColumnBinaryOpExpression("**", self, other)

    def __rpow__(self, other):
        return ColumnBinaryOpExpression("**", other, self)

    def __matmul__(self, other):
        return ColumnBinaryOpExpression("@", self, other)

    def __rmatmul__(self, other):
        return ColumnBinaryOpExpression("@", other, self)

    def __neg__(self):
        return ColumnUnaryOpExpression("-", self)

    def __abs__(self):
        return MethodCallExpression("abs", abs, dt.ANY, [self])

    # -- comparison --
    def __eq__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression("==", self, other)

    def __ne__(self, other):  # type: ignore[override]
        return ColumnBinaryOpExpression("!=", self, other)

    def __lt__(self, other):
        return ColumnBinaryOpExpression("<", self, other)

    def __le__(self, other):
        return ColumnBinaryOpExpression("<=", self, other)

    def __gt__(self, other):
        return ColumnBinaryOpExpression(">", self, other)

    def __ge__(self, other):
        return ColumnBinaryOpExpression(">=", self, other)

    # -- boolean / bitwise --
    def __and__(self, other):
        return ColumnBinaryOpExpression("&", self, other)

    def __rand__(self, other):
        return ColumnBinaryOpExpression("&", other, self)

    def __or__(self, other):
        return ColumnBinaryOpExpression("|", self, other)

    def __ror__(self, other):
        return ColumnBinaryOpExpression("|", other, self)

    def __xor__(self, other):
        return ColumnBinaryOpExpression("^", self, other)

    def __rxor__(self, other):
        return ColumnBinaryOpExpression("^", other, self)

    def __invert__(self):
        return ColumnUnaryOpExpression("~", self)

    def __hash__(self):
        return object.__hash__(self)

    def __bool__(self):
        raise RuntimeError(
            "Cannot use a Pathway expression as a boolean; "
            "use & | ~ instead of and/or/not"
        )

    # -- indexing / methods --
    def __getitem__(self, item):
        return SequenceGetExpression(self, item, check_if_exists=False)

    def get(self, index, default=None):
        return SequenceGetExpression(self, index, default=default, check_if_exists=True)

    def is_none(self):
        return IsNoneExpression(self)

    def is_not_none(self):
        return IsNotNoneExpression(self)

    def as_int(self, unwrap: bool = False, **kw):
        return ConvertExpression(dt.INT, self, unwrap=unwrap)

    def as_float(self, unwrap: bool = False, **kw):
        return ConvertExpression(dt.FLOAT, self, unwrap=unwrap)

    def as_str(self, unwrap: bool = False, **kw):
        return ConvertExpression(dt.STR, self, unwrap=unwrap)

    def as_bool(self, unwrap: bool = False, **kw):
        return ConvertExpression(dt.BOOL, self, unwrap=unwrap)

    def to_string(self):
        return MethodCallExpression(
            "to_string", lambda v: repr(v) if isinstance(v, Json) else str(v), dt.STR, [self]
        )

    # namespaces
    @property
    def dt(self):
        from pathway_tpu.internals.expressions.date_time import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def num(self):
        from pathway_tpu.internals.expressions.numerical import NumericalNamespace

        return NumericalNamespace(self)

    @property
    def str(self):
        from pathway_tpu.internals.expressions.string import StringNamespace

        return StringNamespace(self)

    # -- internals --
    def _sub_expressions(self) -> Iterable["ColumnExpression"]:
        return ()

    def _substitute(self, mapping) -> "ColumnExpression":
        """Rebuild with substituted sub-expressions (desugaring)."""
        return self

    def _infer_dtype(self, resolver: Callable[["ColumnReference"], dt.DType]) -> dt.DType:
        return dt.ANY


ColumnExpressionOrValue = Any


def _wrap(value: ColumnExpressionOrValue) -> ColumnExpression:
    if isinstance(value, ColumnExpression):
        return value
    return ColumnConstExpression(value)


class ColumnConstExpression(ColumnExpression):
    __slots__ = ("_val",)

    def __init__(self, val: Any):
        self._val = val

    def __repr__(self):
        return repr(self._val)

    def _infer_dtype(self, resolver):
        return dt.dtype_of_value(self._val)


class ColumnReference(ColumnExpression):
    """``table.colname`` / ``pw.this.colname`` — a reference to a column."""

    __slots__ = ("_table", "_name")

    def __init__(self, table, name: str):
        self._table = table
        self._name = name

    @property
    def table(self):
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def __repr__(self):
        return f"<{self._table!r}>.{self._name}"

    def _substitute(self, mapping):
        new_table = mapping.get(id(self._table), self._table)
        if new_table is not self._table:
            return ColumnReference(new_table, self._name)
        return self

    def _infer_dtype(self, resolver):
        return resolver(self)


class ColumnBinaryOpExpression(ColumnExpression):
    __slots__ = ("_op", "_left", "_right")

    def __init__(self, op: str, left, right):
        self._op = op
        self._left = _wrap(left)
        self._right = _wrap(right)

    def __repr__(self):
        return f"({self._left!r} {self._op} {self._right!r})"

    def _sub_expressions(self):
        return (self._left, self._right)

    def _substitute(self, mapping):
        return ColumnBinaryOpExpression(
            self._op, self._left._substitute(mapping), self._right._substitute(mapping)
        )

    def _infer_dtype(self, resolver):
        lt = self._left._infer_dtype(resolver)
        rt = self._right._infer_dtype(resolver)
        op = self._op
        if op in ("==", "!=", "<", "<=", ">", ">="):
            return dt.BOOL
        if op in ("&", "|", "^"):
            if lt is dt.INT and rt is dt.INT:
                return dt.INT
            return dt.BOOL
        lt_b, rt_b = lt.strip_optional(), rt.strip_optional()
        optional = lt.is_optional() or rt.is_optional()

        def opt(t):
            return dt.Optional(t) if optional and t is not dt.ANY else t

        if op == "/":
            if lt_b in (dt.INT, dt.FLOAT) and rt_b in (dt.INT, dt.FLOAT):
                return opt(dt.FLOAT)
            if lt_b is dt.DURATION:
                return opt(dt.FLOAT if rt_b is dt.DURATION else dt.DURATION)
        if op == "//":
            if lt_b is dt.INT and rt_b is dt.INT:
                return opt(dt.INT)
            if lt_b is dt.DURATION and rt_b is dt.DURATION:
                return opt(dt.INT)
        if op in ("+", "-", "*", "%", "**"):
            if lt_b is dt.FLOAT or rt_b is dt.FLOAT:
                if lt_b in (dt.INT, dt.FLOAT) and rt_b in (dt.INT, dt.FLOAT):
                    return opt(dt.FLOAT)
            if lt_b is dt.INT and rt_b is dt.INT:
                return opt(dt.INT)
            if lt_b is dt.STR and op in ("+", "*"):
                return opt(dt.STR)
            if lt_b in (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC):
                if op == "-" and rt_b is lt_b:
                    return opt(dt.DURATION)
                if rt_b is dt.DURATION:
                    return opt(lt_b)
            if lt_b is dt.DURATION:
                if op == "+" and rt_b in (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC):
                    return opt(rt_b)
                if rt_b is dt.DURATION and op in ("+", "-"):
                    return opt(dt.DURATION)
                if rt_b is dt.INT and op in ("*",):
                    return opt(dt.DURATION)
            if isinstance(lt_b, dt._Array) or isinstance(rt_b, dt._Array):
                return dt.ANY_ARRAY
            if lt_b is dt.ANY_TUPLE or rt_b is dt.ANY_TUPLE or isinstance(lt_b, dt._Tuple):
                if op == "+":
                    return dt.ANY_TUPLE
        if op == "@":
            return dt.ANY_ARRAY
        return dt.ANY


class ColumnUnaryOpExpression(ColumnExpression):
    __slots__ = ("_op", "_expr")

    def __init__(self, op: str, expr):
        self._op = op
        self._expr = _wrap(expr)

    def __repr__(self):
        return f"{self._op}{self._expr!r}"

    def _sub_expressions(self):
        return (self._expr,)

    def _substitute(self, mapping):
        return ColumnUnaryOpExpression(self._op, self._expr._substitute(mapping))

    def _infer_dtype(self, resolver):
        if self._op == "~":
            return dt.BOOL
        return self._expr._infer_dtype(resolver)


class ReducerExpression(ColumnExpression):
    """A reducer applied inside groupby().reduce() — e.g. pw.reducers.sum(x)."""

    __slots__ = ("_reducer", "_args", "_kwargs")

    def __init__(self, reducer, *args, **kwargs):
        self._reducer = reducer
        self._args = tuple(_wrap(a) for a in args)
        self._kwargs = kwargs

    def __repr__(self):
        return f"pw.reducers.{self._reducer.name}({', '.join(map(repr, self._args))})"

    def _sub_expressions(self):
        return self._args

    def _substitute(self, mapping):
        new = ReducerExpression(self._reducer)
        new._args = tuple(a._substitute(mapping) for a in self._args)
        new._kwargs = self._kwargs
        return new

    def _infer_dtype(self, resolver):
        return self._reducer.result_dtype(
            [a._infer_dtype(resolver) for a in self._args]
        )


class ApplyExpression(ColumnExpression):
    __slots__ = ("_fun", "_return_type", "_args", "_kwargs", "_propagate_none", "_deterministic", "_max_batch_size")

    def __init__(
        self,
        fun: Callable,
        return_type,
        *args,
        _propagate_none: bool = False,
        _deterministic: bool = True,
        _max_batch_size: int | None = None,
        **kwargs,
    ):
        self._fun = fun
        self._return_type = dt.wrap(return_type) if return_type is not None else dt.ANY
        self._args = tuple(_wrap(a) for a in args)
        self._kwargs = {k: _wrap(v) for k, v in kwargs.items()}
        self._propagate_none = _propagate_none
        self._deterministic = _deterministic
        self._max_batch_size = _max_batch_size

    def __repr__(self):
        return f"pw.apply({getattr(self._fun, '__name__', self._fun)!r}, ...)"

    def _sub_expressions(self):
        return self._args + tuple(self._kwargs.values())

    def _substitute(self, mapping):
        new = type(self)(self._fun, self._return_type)
        new._args = tuple(a._substitute(mapping) for a in self._args)
        new._kwargs = {k: v._substitute(mapping) for k, v in self._kwargs.items()}
        new._propagate_none = self._propagate_none
        new._deterministic = self._deterministic
        new._max_batch_size = self._max_batch_size
        return new

    def _infer_dtype(self, resolver):
        return self._return_type


class AsyncApplyExpression(ApplyExpression):
    """Apply of an async fn — rows of a batch awaited concurrently (§3.3)."""


class FullyAsyncApplyExpression(ApplyExpression):
    """Non-blocking async apply: results arrive at later epochs (AsyncTransformer-style)."""

    autocommit_duration_ms: int | None = 100


class CastExpression(ColumnExpression):
    __slots__ = ("_return_type", "_expr")

    def __init__(self, return_type, expr):
        self._return_type = dt.wrap(return_type)
        self._expr = _wrap(expr)

    def __repr__(self):
        return f"pw.cast({self._return_type!r}, {self._expr!r})"

    def _sub_expressions(self):
        return (self._expr,)

    def _substitute(self, mapping):
        return CastExpression(self._return_type, self._expr._substitute(mapping))

    def _infer_dtype(self, resolver):
        inner = self._expr._infer_dtype(resolver)
        if inner.is_optional() and not self._return_type.is_optional():
            return dt.Optional(self._return_type)
        return self._return_type


class ConvertExpression(ColumnExpression):
    """as_int/as_float/as_str/as_bool — JSON-aware conversions."""

    __slots__ = ("_return_type", "_expr", "_unwrap")

    def __init__(self, return_type, expr, unwrap: bool = False):
        self._return_type = dt.wrap(return_type)
        self._expr = _wrap(expr)
        self._unwrap = unwrap

    def _sub_expressions(self):
        return (self._expr,)

    def _substitute(self, mapping):
        return ConvertExpression(self._return_type, self._expr._substitute(mapping), self._unwrap)

    def _infer_dtype(self, resolver):
        return self._return_type if self._unwrap else dt.Optional(self._return_type)


class DeclareTypeExpression(ColumnExpression):
    __slots__ = ("_return_type", "_expr")

    def __init__(self, return_type, expr):
        self._return_type = dt.wrap(return_type)
        self._expr = _wrap(expr)

    def _sub_expressions(self):
        return (self._expr,)

    def _substitute(self, mapping):
        return DeclareTypeExpression(self._return_type, self._expr._substitute(mapping))

    def _infer_dtype(self, resolver):
        return self._return_type


class CoalesceExpression(ColumnExpression):
    __slots__ = ("_args",)

    def __init__(self, *args):
        self._args = tuple(_wrap(a) for a in args)

    def _sub_expressions(self):
        return self._args

    def _substitute(self, mapping):
        new = CoalesceExpression()
        new._args = tuple(a._substitute(mapping) for a in self._args)
        return new

    def _infer_dtype(self, resolver):
        result: dt.DType | None = None
        for a in self._args:
            t = a._infer_dtype(resolver)
            result = t if result is None else dt.types_lca(result, t)
        if result is None:
            return dt.ANY
        # if any argument is non-optional, the result is non-optional
        if any(not a._infer_dtype(resolver).is_optional() for a in self._args):
            return dt.unoptionalize(result)
        return result


class RequireExpression(ColumnExpression):
    __slots__ = ("_val", "_args")

    def __init__(self, val, *args):
        self._val = _wrap(val)
        self._args = tuple(_wrap(a) for a in args)

    def _sub_expressions(self):
        return (self._val, *self._args)

    def _substitute(self, mapping):
        return RequireExpression(
            self._val._substitute(mapping), *[a._substitute(mapping) for a in self._args]
        )

    def _infer_dtype(self, resolver):
        return dt.Optional(self._val._infer_dtype(resolver))


class IfElseExpression(ColumnExpression):
    __slots__ = ("_if", "_then", "_else")

    def __init__(self, _if, _then, _else):
        self._if = _wrap(_if)
        self._then = _wrap(_then)
        self._else = _wrap(_else)

    def _sub_expressions(self):
        return (self._if, self._then, self._else)

    def _substitute(self, mapping):
        return IfElseExpression(
            self._if._substitute(mapping),
            self._then._substitute(mapping),
            self._else._substitute(mapping),
        )

    def _infer_dtype(self, resolver):
        return dt.types_lca(
            self._then._infer_dtype(resolver), self._else._infer_dtype(resolver)
        )


class IsNoneExpression(ColumnExpression):
    __slots__ = ("_expr",)

    def __init__(self, expr):
        self._expr = _wrap(expr)

    def _sub_expressions(self):
        return (self._expr,)

    def _substitute(self, mapping):
        # type(self): IsNotNoneExpression inherits this — substituting must
        # not collapse it into the base class
        return type(self)(self._expr._substitute(mapping))

    def _infer_dtype(self, resolver):
        return dt.BOOL


class IsNotNoneExpression(IsNoneExpression):
    pass


class MakeTupleExpression(ColumnExpression):
    __slots__ = ("_args",)

    def __init__(self, *args):
        self._args = tuple(_wrap(a) for a in args)

    def _sub_expressions(self):
        return self._args

    def _substitute(self, mapping):
        new = MakeTupleExpression()
        new._args = tuple(a._substitute(mapping) for a in self._args)
        return new

    def _infer_dtype(self, resolver):
        return dt.Tuple(*[a._infer_dtype(resolver) for a in self._args])


class SequenceGetExpression(ColumnExpression):
    __slots__ = ("_obj", "_index", "_default", "_check_if_exists")

    def __init__(self, obj, index, default=None, check_if_exists: bool = True):
        self._obj = _wrap(obj)
        self._index = _wrap(index)
        self._default = _wrap(default)
        self._check_if_exists = check_if_exists

    def _sub_expressions(self):
        return (self._obj, self._index, self._default)

    def _substitute(self, mapping):
        new = SequenceGetExpression(
            self._obj._substitute(mapping),
            self._index._substitute(mapping),
            check_if_exists=self._check_if_exists,
        )
        new._default = self._default._substitute(mapping)
        return new

    def _infer_dtype(self, resolver):
        obj_t = self._obj._infer_dtype(resolver).strip_optional()
        if obj_t is dt.JSON:
            return dt.Optional(dt.JSON) if self._check_if_exists else dt.JSON
        if isinstance(obj_t, dt._List):
            return obj_t.wrapped
        if isinstance(obj_t, dt._Tuple) and obj_t.args is not Ellipsis:
            if isinstance(self._index, ColumnConstExpression) and isinstance(
                self._index._val, int
            ):
                i = self._index._val
                if -len(obj_t.args) <= i < len(obj_t.args):
                    return obj_t.args[i]
        if obj_t is dt.STR:
            return dt.STR
        if isinstance(obj_t, dt._Array):
            return dt.ANY
        return dt.ANY


class MethodCallExpression(ColumnExpression):
    """A namespaced method (x.dt.year(), x.str.lower(), ...) with a host impl."""

    __slots__ = ("_method_name", "_fun", "_return_type", "_args", "_kwargs", "_propagate_none")

    def __init__(self, method_name: str, fun: Callable, return_type, args, kwargs=None, propagate_none=True):
        self._method_name = method_name
        self._fun = fun
        self._return_type = return_type
        self._args = tuple(_wrap(a) for a in args)
        self._kwargs = {k: _wrap(v) for k, v in (kwargs or {}).items()}
        self._propagate_none = propagate_none

    def __repr__(self):
        return f".{self._method_name}({', '.join(map(repr, self._args[1:]))})"

    def _sub_expressions(self):
        return self._args + tuple(self._kwargs.values())

    def _substitute(self, mapping):
        new = MethodCallExpression(
            self._method_name, self._fun, self._return_type, []
        )
        new._args = tuple(a._substitute(mapping) for a in self._args)
        new._kwargs = {k: v._substitute(mapping) for k, v in self._kwargs.items()}
        new._propagate_none = self._propagate_none
        return new

    def _infer_dtype(self, resolver):
        if callable(self._return_type) and not isinstance(self._return_type, dt.DType):
            return self._return_type([a._infer_dtype(resolver) for a in self._args])
        return dt.wrap(self._return_type)


class UnwrapExpression(ColumnExpression):
    __slots__ = ("_expr",)

    def __init__(self, expr):
        self._expr = _wrap(expr)

    def _sub_expressions(self):
        return (self._expr,)

    def _substitute(self, mapping):
        return UnwrapExpression(self._expr._substitute(mapping))

    def _infer_dtype(self, resolver):
        return dt.unoptionalize(self._expr._infer_dtype(resolver))


class FillErrorExpression(ColumnExpression):
    __slots__ = ("_expr", "_replacement")

    def __init__(self, expr, replacement):
        self._expr = _wrap(expr)
        self._replacement = _wrap(replacement)

    def _sub_expressions(self):
        return (self._expr, self._replacement)

    def _substitute(self, mapping):
        return FillErrorExpression(
            self._expr._substitute(mapping), self._replacement._substitute(mapping)
        )

    def _infer_dtype(self, resolver):
        return dt.types_lca(
            self._expr._infer_dtype(resolver), self._replacement._infer_dtype(resolver)
        )


class PointerExpression(ColumnExpression):
    """``table.pointer_from(*args)`` — derive a row id."""

    __slots__ = ("_table", "_args", "_optional", "_instance")

    def __init__(self, table, *args, optional: bool = False, instance=None):
        self._table = table
        self._args = tuple(_wrap(a) for a in args)
        self._optional = optional
        self._instance = instance

    def _sub_expressions(self):
        return self._args

    def _substitute(self, mapping):
        new = PointerExpression(
            mapping.get(id(self._table), self._table), optional=self._optional
        )
        new._args = tuple(a._substitute(mapping) for a in self._args)
        new._instance = self._instance
        return new

    def _infer_dtype(self, resolver):
        return dt.Optional(dt.POINTER) if self._optional else dt.POINTER


# --- free functions (exported at pw top level) --------------------------------


def apply(fun: Callable, *args, **kwargs) -> ColumnExpression:
    """``pw.apply`` — row-wise application of a Python function."""
    import typing as _t

    hints = {}
    try:
        hints = _t.get_type_hints(fun)
    except Exception:
        pass
    ret = hints.get("return")
    return ApplyExpression(fun, ret, *args, **kwargs)


def apply_with_type(fun: Callable, ret_type, *args, **kwargs) -> ColumnExpression:
    return ApplyExpression(fun, ret_type, *args, **kwargs)


def apply_async(fun: Callable, *args, **kwargs) -> ColumnExpression:
    import typing as _t

    hints = {}
    try:
        hints = _t.get_type_hints(fun)
    except Exception:
        pass
    return AsyncApplyExpression(fun, hints.get("return"), *args, **kwargs)


def cast(target_type, expr) -> CastExpression:
    return CastExpression(target_type, expr)


def declare_type(target_type, expr) -> DeclareTypeExpression:
    return DeclareTypeExpression(target_type, expr)


def coalesce(*args) -> CoalesceExpression:
    return CoalesceExpression(*args)


def require(val, *args) -> RequireExpression:
    return RequireExpression(val, *args)


def if_else(_if, _then, _else) -> IfElseExpression:
    return IfElseExpression(_if, _then, _else)


def make_tuple(*args) -> MakeTupleExpression:
    return MakeTupleExpression(*args)


def unwrap(expr) -> UnwrapExpression:
    return UnwrapExpression(expr)


def fill_error(expr, replacement) -> FillErrorExpression:
    return FillErrorExpression(expr, replacement)


def assert_table_has_schema(table, schema, *, allow_superset: bool = True, ignore_primary_keys: bool = True) -> None:
    table.schema.assert_matches_schema(
        schema, allow_superset=allow_superset, ignore_primary_keys=ignore_primary_keys
    )
