"""Lazy build graph.

Parity target: ``/root/reference/python/pathway/internals/parse_graph.py``
(255 LoC).  User Table operations register *recipes*; nothing executes until
``pw.run()`` / ``pw.debug.compute_and_print``.  The global graph ``G`` tracks
sinks (output/subscribe operators) and all created tables so the runner can
tree-shake and execute, and so tests can ``G.clear()`` between cases.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable


class ParseGraph:
    def __init__(self):
        self.clear()

    def clear(self) -> None:
        # sinks: list of (name, table, attach) where attach(lowerer, node) -> poller list
        self.sinks: list[tuple[str, Any, Callable]] = []
        self.tables: list[Any] = []
        self._id_counter = itertools.count()
        self.error_log_stack: list[Any] = []

    # mirrors G.clear() used throughout reference tests
    def new_table(self, table: Any) -> None:
        self.tables.append(table)

    def add_sink(self, name: str, table: Any, attach: Callable) -> None:
        self.sinks.append((name, table, attach))

    def next_id(self) -> int:
        return next(self._id_counter)


G = ParseGraph()
