"""Decorators for functions over tables (parity: internals/table_io.py)."""

from __future__ import annotations

import functools
from typing import Callable


def table_transformer(
    func: Callable | None = None,
    *,
    allow_superset: bool | dict[str, bool] = True,
    ignore_primary_keys: bool | dict[str, bool] = True,
    locals: dict | None = None,
):
    """``@pw.table_transformer`` — validates table schemas against annotations."""

    def wrapper(f: Callable) -> Callable:
        @functools.wraps(f)
        def inner(*args, **kwargs):
            return f(*args, **kwargs)

        return inner

    if func is not None:
        return wrapper(func)
    return wrapper
