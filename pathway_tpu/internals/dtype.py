"""Dtype lattice for schema/type inference.

Parity target: ``/root/reference/python/pathway/internals/dtype.py`` (979 LoC).
Provides the same user-observable surface — singleton dtypes, ``Optional``,
``Tuple``/``List``/``Array``, conversion from Python annotations, and a least
upper bound used by type inference — without the reference's torch-style
caching metaclass machinery.
"""

from __future__ import annotations

import datetime
import types as _pytypes
import typing
from typing import Any

import numpy as np

from pathway_tpu.engine import types as _etypes

try:  # resolved once; coerce() runs per cell and must not retry imports
    import jax as _jax
except ImportError:  # pragma: no cover
    _jax = None


class DType:
    """Base of all dtypes. Instances are immutable and hash-consed."""

    _cache: dict[Any, "DType"] = {}

    def is_value_compatible(self, value: Any) -> bool:
        raise NotImplementedError

    def to_python_type(self):
        return object

    @property
    def typehint(self):
        return self.to_python_type()

    def __repr__(self) -> str:
        return self.__class__.__name__

    def equivalent_to(self, other: "DType") -> bool:
        return self == other

    def is_subclass_of(self, other: "DType") -> bool:
        if other is ANY or self == other:
            return True
        if isinstance(other, _Optional):
            if self is NONE:
                return True
            inner = self.strip_optional()
            return inner.is_subclass_of(other.wrapped) and (
                not isinstance(self, _Optional) or True
            )
        if self is INT and other is FLOAT:
            return True
        if isinstance(self, _Tuple) and isinstance(other, _Tuple):
            if other.args is Ellipsis:
                return True
            if self.args is Ellipsis:
                return False
            if len(self.args) != len(other.args):
                return False
            return all(a.is_subclass_of(b) for a, b in zip(self.args, other.args))
        return False

    def strip_optional(self) -> "DType":
        return self

    def is_optional(self) -> bool:
        return isinstance(self, _Optional) or self is ANY or self is NONE


class _SimpleDType(DType):
    __slots__ = ("name", "_ptype", "_compat")

    def __new__(cls, name: str, ptype, compat):
        key = ("simple", name)
        if key not in DType._cache:
            obj = super().__new__(cls)
            obj.name = name
            obj._ptype = ptype
            obj._compat = compat
            DType._cache[key] = obj
        return DType._cache[key]

    def __repr__(self) -> str:
        return self.name

    def to_python_type(self):
        return self._ptype

    def is_value_compatible(self, value: Any) -> bool:
        return self._compat(value)


ANY = _SimpleDType("ANY", object, lambda v: True)
NONE = _SimpleDType("NONE", type(None), lambda v: v is None)
INT = _SimpleDType("INT", int, lambda v: isinstance(v, (int, np.integer)) and not isinstance(v, bool))
FLOAT = _SimpleDType(
    "FLOAT", float, lambda v: isinstance(v, (int, float, np.floating, np.integer)) and not isinstance(v, bool)
)
BOOL = _SimpleDType("BOOL", bool, lambda v: isinstance(v, (bool, np.bool_)))
STR = _SimpleDType("STR", str, lambda v: isinstance(v, str))
BYTES = _SimpleDType("BYTES", bytes, lambda v: isinstance(v, bytes))
POINTER = _SimpleDType("POINTER", _etypes.Pointer, lambda v: isinstance(v, _etypes.Pointer))
DATE_TIME_NAIVE = _SimpleDType(
    "DATE_TIME_NAIVE",
    datetime.datetime,
    lambda v: isinstance(v, datetime.datetime) and v.tzinfo is None,
)
DATE_TIME_UTC = _SimpleDType(
    "DATE_TIME_UTC",
    datetime.datetime,
    lambda v: isinstance(v, datetime.datetime) and v.tzinfo is not None,
)
DURATION = _SimpleDType("DURATION", datetime.timedelta, lambda v: isinstance(v, datetime.timedelta))
JSON = _SimpleDType("JSON", _etypes.Json, lambda v: isinstance(v, _etypes.Json))
ERROR = _SimpleDType("ERROR", _etypes.Error, lambda v: isinstance(v, _etypes.Error))
PY_OBJECT_WRAPPER = _SimpleDType(
    "PY_OBJECT_WRAPPER", _etypes.PyObjectWrapper, lambda v: isinstance(v, _etypes.PyObjectWrapper)
)
FUTURE = _SimpleDType("FUTURE", object, lambda v: True)  # pending async results


class _Optional(DType):
    __slots__ = ("wrapped",)

    def __new__(cls, wrapped: DType):
        if isinstance(wrapped, _Optional) or wrapped in (ANY, NONE):
            return wrapped
        key = ("optional", wrapped)
        if key not in DType._cache:
            obj = super().__new__(cls)
            obj.wrapped = wrapped
            DType._cache[key] = obj
        return DType._cache[key]

    def __repr__(self) -> str:
        return f"Optional({self.wrapped!r})"

    def to_python_type(self):
        return typing.Optional[self.wrapped.to_python_type()]

    def is_value_compatible(self, value: Any) -> bool:
        return value is None or self.wrapped.is_value_compatible(value)

    def strip_optional(self) -> DType:
        return self.wrapped


def Optional(wrapped: DType) -> DType:  # noqa: N802  (mirrors dt.Optional)
    return _Optional(wrapped)


class _Pointer(DType):
    """Typed pointer Pointer[S] — equivalent to POINTER for runtime purposes."""

    __slots__ = ("schema",)

    def __new__(cls, schema=None):
        key = ("pointer", schema)
        if key not in DType._cache:
            obj = super().__new__(cls)
            obj.schema = schema
            DType._cache[key] = obj
        return DType._cache[key]

    def __repr__(self) -> str:
        return "POINTER" if self.schema is None else f"Pointer({self.schema.__name__})"

    def to_python_type(self):
        return _etypes.Pointer

    def is_value_compatible(self, value: Any) -> bool:
        return isinstance(value, _etypes.Pointer)

    def is_subclass_of(self, other: DType) -> bool:
        if other is POINTER or isinstance(other, _Pointer):
            return True
        return super().is_subclass_of(other)


def Pointer(schema=None) -> DType:  # noqa: N802
    if schema is None:
        return POINTER
    return _Pointer(schema)


class _Tuple(DType):
    __slots__ = ("args",)

    def __new__(cls, args):
        key = ("tuple", args if args is Ellipsis else tuple(args))
        if key not in DType._cache:
            obj = super().__new__(cls)
            obj.args = args if args is Ellipsis else tuple(args)
            DType._cache[key] = obj
        return DType._cache[key]

    def __repr__(self) -> str:
        if self.args is Ellipsis:
            return "Tuple(...)"
        return f"Tuple({', '.join(map(repr, self.args))})"

    def to_python_type(self):
        return tuple

    def is_value_compatible(self, value: Any) -> bool:
        if not isinstance(value, tuple):
            return False
        if self.args is Ellipsis:
            return True
        return len(value) == len(self.args) and all(
            a.is_value_compatible(v) for a, v in zip(self.args, value)
        )


def Tuple(*args) -> DType:  # noqa: N802
    if len(args) == 1 and args[0] is Ellipsis:
        return _Tuple(Ellipsis)
    return _Tuple(tuple(wrap(a) if not isinstance(a, DType) else a for a in args))


ANY_TUPLE = _Tuple(Ellipsis)


class _List(DType):
    __slots__ = ("wrapped",)

    def __new__(cls, wrapped: DType):
        key = ("list", wrapped)
        if key not in DType._cache:
            obj = super().__new__(cls)
            obj.wrapped = wrapped
            DType._cache[key] = obj
        return DType._cache[key]

    def __repr__(self) -> str:
        return f"List({self.wrapped!r})"

    def to_python_type(self):
        return tuple  # lists are normalized to tuples in the engine

    def is_value_compatible(self, value: Any) -> bool:
        return isinstance(value, (tuple, list)) and all(
            self.wrapped.is_value_compatible(v) for v in value
        )


def List(wrapped) -> DType:  # noqa: N802
    return _List(wrap_inner(wrapped))


class _Array(DType):
    """N-dimensional numeric array (maps to jax/np arrays on device)."""

    __slots__ = ("n_dim", "wrapped")

    def __new__(cls, n_dim=None, wrapped=None):
        key = ("array", n_dim, wrapped)
        if key not in DType._cache:
            obj = super().__new__(cls)
            obj.n_dim = n_dim
            obj.wrapped = wrapped
            DType._cache[key] = obj
        return DType._cache[key]

    def __repr__(self) -> str:
        return f"Array({self.n_dim}, {self.wrapped!r})"

    def to_python_type(self):
        return np.ndarray

    def is_value_compatible(self, value: Any) -> bool:
        if not isinstance(value, np.ndarray):
            try:  # jax arrays quack like ndarrays
                import jax

                if isinstance(value, jax.Array):
                    return True
            except Exception:
                pass
            return False
        return self.n_dim is None or value.ndim == self.n_dim

    def is_subclass_of(self, other: DType) -> bool:
        if isinstance(other, _Array) and other.n_dim is None:
            return True
        return super().is_subclass_of(other)


def Array(n_dim=None, wrapped=None) -> DType:  # noqa: N802
    return _Array(n_dim, wrapped)


ANY_ARRAY = _Array(None, None)
INT_ARRAY = _Array(None, INT)
FLOAT_ARRAY = _Array(None, FLOAT)


class _Callable(DType):
    __slots__ = ("arg_types", "return_type")

    def __new__(cls, arg_types, return_type):
        key = ("callable", arg_types if arg_types is Ellipsis else tuple(arg_types), return_type)
        if key not in DType._cache:
            obj = super().__new__(cls)
            obj.arg_types = arg_types
            obj.return_type = return_type
            DType._cache[key] = obj
        return DType._cache[key]

    def __repr__(self) -> str:
        return f"Callable(..., {self.return_type!r})"

    def is_value_compatible(self, value: Any) -> bool:
        return callable(value)


def Callable(arg_types=Ellipsis, return_type=ANY) -> DType:  # noqa: N802
    return _Callable(arg_types, return_type)


# --- conversion from Python annotations --------------------------------------

_SIMPLE_FROM_PY = {
    int: INT,
    float: FLOAT,
    bool: BOOL,
    str: STR,
    bytes: BYTES,
    type(None): NONE,
    Any: ANY,
    np.ndarray: ANY_ARRAY,
    datetime.datetime: DATE_TIME_NAIVE,
    datetime.timedelta: DURATION,
    _etypes.Pointer: POINTER,
    _etypes.Json: JSON,
    _etypes.PyObjectWrapper: PY_OBJECT_WRAPPER,
    dict: JSON,
}


def wrap(input_type) -> DType:
    """Convert a Python type annotation (or DType) into a DType."""
    if isinstance(input_type, DType):
        return input_type
    if input_type is None:
        return NONE
    if input_type in _SIMPLE_FROM_PY:
        return _SIMPLE_FROM_PY[input_type]
    origin = typing.get_origin(input_type)
    args = typing.get_args(input_type)
    if origin is typing.Union or origin is _pytypes.UnionType:  # X | None (PEP 604)
        non_none = [a for a in args if a is not type(None)]
        has_none = len(non_none) != len(args)
        if len(non_none) == 1:
            inner = wrap(non_none[0])
            return _Optional(inner) if has_none else inner
        return ANY
    if origin in (tuple,):
        if len(args) == 2 and args[1] is Ellipsis:
            return _List(wrap(args[0]))
        return Tuple(*[wrap(a) for a in args])
    if origin in (list,):
        return _List(wrap(args[0])) if args else _List(ANY)
    if origin in (dict,):
        return JSON
    if origin is typing.Callable or origin is getattr(__import__("collections.abc", fromlist=["Callable"]), "Callable", None):
        return Callable(Ellipsis, wrap(args[1]) if len(args) == 2 else ANY)
    if isinstance(input_type, type):
        # pw.Pointer[Schema] style subscripted generics fall here as plain class
        if issubclass(input_type, _etypes.Pointer):
            return POINTER
    try:
        if str(input_type).startswith("pathway"):
            return ANY
    except Exception:
        pass
    return ANY


def wrap_inner(t) -> DType:
    return t if isinstance(t, DType) else wrap(t)


def unoptionalize(t: DType) -> DType:
    return t.strip_optional()


def types_lca(a: DType, b: DType, *, raising: bool = False) -> DType:
    """Least common ancestor in the lattice (used by if_else/coalesce/concat)."""
    if a == b:
        return a
    if a is ERROR:
        return b
    if b is ERROR:
        return a
    if a is NONE:
        return _Optional(b)
    if b is NONE:
        return _Optional(a)
    a_opt = isinstance(a, _Optional)
    b_opt = isinstance(b, _Optional)
    if a_opt or b_opt:
        inner = types_lca(unoptionalize(a), unoptionalize(b), raising=raising)
        return _Optional(inner)
    if {a, b} == {INT, FLOAT}:
        return FLOAT
    if isinstance(a, _Pointer) and (isinstance(b, _Pointer) or b is POINTER):
        return POINTER
    if isinstance(b, _Pointer) and a is POINTER:
        return POINTER
    if isinstance(a, _Tuple) and isinstance(b, _Tuple):
        if a.args is Ellipsis or b.args is Ellipsis or len(a.args) != len(b.args):
            return ANY_TUPLE
        return _Tuple(tuple(types_lca(x, y, raising=raising) for x, y in zip(a.args, b.args)))
    if isinstance(a, _Array) and isinstance(b, _Array):
        return _Array(a.n_dim if a.n_dim == b.n_dim else None, None)
    if raising:
        raise TypeError(f"cannot find common type for {a!r} and {b!r}")
    return ANY


def dtype_of_value(value: Any) -> DType:
    if value is None:
        return NONE
    if isinstance(value, bool):
        return BOOL
    if isinstance(value, int):
        return INT
    if isinstance(value, float):
        return FLOAT
    if isinstance(value, str):
        return STR
    if isinstance(value, bytes):
        return BYTES
    if isinstance(value, _etypes.Pointer):
        return POINTER
    if isinstance(value, _etypes.Json):
        return JSON
    if isinstance(value, _etypes.Error):
        return ERROR
    if isinstance(value, _etypes.PyObjectWrapper):
        return PY_OBJECT_WRAPPER
    if isinstance(value, datetime.datetime):
        return DATE_TIME_UTC if value.tzinfo is not None else DATE_TIME_NAIVE
    if isinstance(value, datetime.timedelta):
        return DURATION
    if isinstance(value, np.ndarray):
        return _Array(value.ndim, INT if np.issubdtype(value.dtype, np.integer) else FLOAT)
    if isinstance(value, tuple):
        return _Tuple(tuple(dtype_of_value(v) for v in value))
    return ANY


# Coercions applied when a value enters a column of a known dtype.
def coerce(value: Any, dtype: DType) -> Any:
    # hot-path exact (value type, dtype) exits — ingest calls this per cell
    t = type(value)
    if (
        (dtype is INT and t is int)
        or (dtype is FLOAT and t is float)
        or (dtype is STR and t is str)
        or (dtype is BOOL and t is bool)
        or (dtype is ANY and (t is int or t is float or t is str or t is bool))
    ):
        return value
    if value is None or isinstance(value, _etypes.Error):
        return value
    if isinstance(value, (np.ndarray, tuple)):
        value = _etypes.as_hashable(value)
        if isinstance(value, _etypes.HashableNDArray):
            return value
    elif _jax is not None and isinstance(value, _jax.Array):
        return _etypes.as_hashable(np.asarray(value))
    base = dtype.strip_optional()
    if base is FLOAT and isinstance(value, (int, np.integer)) and not isinstance(value, bool):
        return float(value)
    if base is INT and isinstance(value, np.integer):
        return int(value)
    if base is JSON and not isinstance(value, _etypes.Json):
        return _etypes.Json(value)
    if isinstance(base, _List) and isinstance(value, list):
        return tuple(value)
    return value
