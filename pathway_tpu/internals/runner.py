"""Graph runner: lowers registered sinks and drives the engine event loop.

Parity target: ``/root/reference/python/pathway/internals/graph_runner/__init__.py``
(the tree-shake → lower → ``run_with_new_graph`` path, §3.1 of SURVEY.md) and
the worker event loop of ``src/engine/dataflow.rs:6051-6104`` (probers →
flushers → pollers → step).  Single-process form: the epoch loop polls
connector queues, picks the next commit timestamp across all input sessions,
and runs one consolidated pass of the operator DAG per epoch.
"""

from __future__ import annotations

import os
import time as _time
from collections import deque
from contextlib import nullcontext as _nullcontext
from typing import Any, Callable

from pathway_tpu.engine import dataflow as df
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.table import Lowerer, Table


class Poller:
    """A connector pump: moves rows from reader threads into InputNodes.

    Mirrors the poller closure pattern (connectors/mod.rs:292; dataflow.rs:6084).
    """

    def poll(self) -> bool:
        """Advance; return True when the source is exhausted."""
        return True


def add_debug_sink(name: str, table: Table) -> None:
    def on_data(key, row, time, diff):
        sign = "+" if diff > 0 else "-"
        print(f"[{name}] {sign} key={key & 0xFFFFFFFF:x} time={time} row={row}")

    table._subscribe_raw(on_data, name=f"debug:{name}")


class RunResult:
    def __init__(self):
        self.epochs = 0
        self.prober = None  # engine.probes.Prober when monitoring ran
        self.telemetry = None  # engine.telemetry.Telemetry for this run
        self.profiler = None  # engine.profiler.EpochProfiler for this run
        self.freshness = None  # engine.freshness.FreshnessTracker for this run
        self.last_time: int | None = None  # last processed epoch
        self.clean_finish = False
        # set when the run exited through a LIVE HANDOFF: the worker
        # drained + fenced its frontier for a planned rescale to this
        # worker count and exited 0 WITHOUT finishing the scope — neither
        # a clean finish nor a failure (the supervisor relaunches at the
        # new topology and the run continues there)
        self.handoff_to: int | None = None
        # an exception escaped mid-run_epoch: node states are inconsistent
        # (some nodes stepped the failing epoch, some did not)
        self.epoch_failed = False


def _graph_digest(scope: df.Scope) -> str:
    """Structural fingerprint for operator-snapshot compatibility.

    Covers node kinds, wiring (input ids/ports), and iterate subscopes.
    Best-effort: changes inside Python callables (UDF bodies, filter
    predicates) are invisible to it — the same limitation the reference has
    with its positionally-matched operator snapshots."""
    import hashlib as _hashlib

    def scope_sig(s: df.Scope) -> str:
        parts = []
        for n in s.nodes:
            wires = ",".join(str(i.id) for i in n.inputs)
            part = f"{n.name}({wires})"
            sub = getattr(n, "subscope", None)
            if sub is not None:
                part += "{" + scope_sig(sub) + "}"
            parts.append(part)
        return ";".join(parts)

    sig = scope_sig(scope)
    return f"{len(scope.nodes)}:{_hashlib.md5(sig.encode()).hexdigest()}"


def _wire_operator_persistence(scope: df.Scope, storage: Any) -> None:
    """Operator-snapshot mode: restore node arrangements from the last
    committed generation, and hand the storage a collector that dumps dirty
    nodes at each commit (persistence/operator_snapshot.rs analog)."""
    import pickle as _pickle

    digest = _graph_digest(scope)
    for node_id, blob in storage.load_operator_states(digest).items():
        scope.nodes[node_id].persist_load(_pickle.loads(blob))
    last_rows_in: dict[int, int] = {n.id: n.rows_in for n in scope.nodes}
    staged_marks: dict[int, int] = {}

    def collect(full: bool):
        # full=True (clean finish): dump everything — on_finish hooks
        # mutate state (buffer drains) without touching rows_in
        dirty: dict[int, bytes] = {}
        staged_marks.clear()
        for node in scope.nodes:
            if not full and node.rows_in == last_rows_in.get(node.id, -1):
                continue
            data = node.persist_dump()
            staged_marks[node.id] = node.rows_in
            if data is not None:
                dirty[node.id] = _pickle.dumps(data)
        return dirty, digest

    def confirm():
        # nodes count as clean only once the metadata referencing their
        # dumps is durably committed — a failed commit must re-dump them
        last_rows_in.update(staged_marks)
        staged_marks.clear()

    storage.collect_operator_states = collect
    storage.confirm_operator_commit = confirm


def run(**kwargs: Any) -> RunResult:
    """``pw.run`` — execute every registered sink to completion.

    ``_sinks`` (internal) runs an explicit sink list instead of the
    graph's registry — ``Table.live()`` uses it to run one export sink's
    cone on a background thread while the interactive graph stays open
    (the reference's ``runner.run_nodes([operator])``).

    Two supervised-run detours wrap the single execution
    (:func:`_run_once`); both are inert for ordinary runs:

    * **standby mode** (``PATHWAY_STANDBY_ID`` exported by the
      supervisor): instead of joining the mesh, the process tails the
      persistence root (``engine/standby.py``) until the supervisor
      either stops it or PROMOTES it into a dead worker's id — at which
      point it falls through into the normal worker path below, already
      wearing the dead worker's identity.
    * **promotion rejoin**: when a PEER dies and a standby is being
      promoted, this worker's mesh is poisoned
      (:class:`~pathway_tpu.engine.comm.MeshPoisoned`) so the run
      unwinds through its normal consistent drain-commit — and then,
      instead of exiting for a whole-group restart, the loop here acks
      the promotion and re-enters ``_run_once`` in-process: fresh mesh,
      fresh graph, zero process-spawn cost, surviving workers never
      restart.
    """
    from pathway_tpu.engine import standby as _standby

    sid = _standby.standby_id()
    if sid is not None:
        root = _persistence_root(kwargs.get("persistence_config"))
        if root is None:
            raise RuntimeError(
                "standby mode (PATHWAY_STANDBY_ID) requires a filesystem "
                "persistence root to tail — spawn with --checkpoints"
            )
        if _standby.standby_main(root, sid) is None:
            return RunResult()  # supervisor shutdown before any promotion
        # promoted: this process adopted the dead worker's identity; fall
        # through into the normal worker path
    while True:
        try:
            return _run_once(**kwargs)
        except BaseException as exc:
            from pathway_tpu.engine.comm import CommError, MeshPoisoned

            # a CommError on a dead peer counts as the poison signal when
            # a promotion naming this incarnation is pending: the link
            # heartbeat and the supervisor race to notice the death, and
            # losing that race must not demote a promotion to a restart
            if not isinstance(exc, MeshPoisoned) and not (
                isinstance(exc, CommError)
                and _pending_promotion(kwargs.get("persistence_config"))
                is not None
            ):
                raise
            _promotion_rejoin(kwargs.get("persistence_config"))


def _run_once(
    *,
    debug: bool = False,
    monitoring_level: Any = None,
    with_http_server: bool = False,
    default_logging: bool = True,
    persistence_config: Any = None,
    runtime_typechecking: bool | None = None,
    terminate_on_error: bool = True,
    max_epochs: int | None = None,
    _sinks: list | None = None,
    **kwargs: Any,
) -> RunResult:
    """One mesh lifetime of ``pw.run`` — see :func:`run` for the
    standby/promotion wrapper that may call this more than once."""
    scope = df.Scope()
    scope.terminate_on_error = terminate_on_error

    # multi-process SPMD: every process runs this same script and builds the
    # identical graph; a TCP mesh exchanges rows by key shard
    # (engine/comm.py; the reference's timely Cluster config analog).
    from pathway_tpu.internals.config import get_config as _get_config

    from pathway_tpu.internals.config import env_bool as _env_bool

    _cfg = _get_config()
    # topology handshake: a supervised worker's mesh size must be the
    # lease-recorded topology, not whatever argv happened to say — an
    # operator relaunching with a stale -n (or a k8s replica count scaled
    # behind the supervisor's back) must fail loudly BEFORE the mesh
    # forms, not resume with a silently mis-sharded cluster
    _topology_handshake(persistence_config, _cfg)
    if _cfg.processes > 1 and _env_bool("PATHWAY_JAX_DISTRIBUTED"):
        # `pathway spawn --jax-distributed`: the host workers double as JAX
        # processes of one global device mesh (DCN between hosts) — must
        # run before any backend init
        from pathway_tpu.parallel.mesh import initialize_distributed

        initialize_distributed()
    worker_ctx = None
    trace_parent = os.environ.get("TRACEPARENT")
    if _cfg.processes > 1:
        from pathway_tpu.engine.comm import TcpMesh, WorkerContext

        mesh = TcpMesh(
            _cfg.process_id,
            _cfg.processes,
            _cfg.first_port,
            peer_hosts=_cfg.peer_hosts,
        ).start()
        worker_ctx = WorkerContext(mesh)
        scope.worker = worker_ctx
        # cross-worker trace correlation: worker 0 mints the run's
        # traceparent (unless the deployment already exported one — `spawn`
        # does) and broadcasts it over the fresh mesh, so epoch/commit/
        # recovery spans from EVERY worker land in one trace
        from pathway_tpu.engine.telemetry import mint_traceparent

        if _cfg.process_id == 0 and not trace_parent:
            trace_parent = mint_traceparent()
        trace_parent = mesh.bcast(("traceparent",), trace_parent)

    lowerer = Lowerer(scope)
    # pw.run(debug=True): connectors with debug_data= lower to static
    # tables of that data (reference operator_handler.py:110)
    lowerer.debug_mode = debug

    storage = _make_storage(persistence_config)
    if storage is not None:
        lowerer.persistence_storage = storage

    # lower all sinks (tree-shaking is implicit: only sink cones are built)
    sink_labels: set[str] = set()
    for name, table, attach in (list(G.sinks) if _sinks is None else _sinks):
        node = lowerer.node(table)
        sink_node = attach(lowerer, node)
        # per-output identity for the freshness/staleness metrics: the
        # registration name is the label operators and dashboards rank
        # by.  Colliding names (two default-named subscribes, or distinct
        # raw names that sanitize to the same label value) get a node id
        # suffix — sharing one label would let a stalled output hide
        # behind a healthy one refreshing the same staleness gauge.
        if isinstance(sink_node, df.OutputNode) and sink_node.sink_name is None:
            from pathway_tpu.engine.freshness import safe_label

            label = safe_label(name)
            if label in sink_labels:
                label = f"{label}#{sink_node.id}"
            sink_node.sink_name = label
            sink_labels.add(label)

    # append-only analysis must run before any state is restored or stepped:
    # GroupByNode picks its accumulator variant off the inferred flags
    df.infer_append_only(scope)

    result = RunResult()
    if storage is not None and storage.operator_persistence:
        _wire_operator_persistence(scope, storage)
    root_token = None
    http_server = None
    persist_root = None  # filesystem persistence root, when there is one
    prev_usr1 = None
    usr1_installed = False
    promote_watcher = None
    try:
        if storage is not None:
            from pathway_tpu.engine import faults as _faults
            from pathway_tpu.engine import persistence as pz

            base_backend = storage.backend
            if isinstance(base_backend, _faults.FlakyBackend):
                base_backend = base_backend.inner  # fault wrapper is I/O-only
            if isinstance(base_backend, pz.FileBackend):
                persist_root = base_backend.root
                # UDF DiskCache shares the persistence root for this run
                # only; acquired inside the try so any failure below still
                # releases it in the finally
                root_token = pz.acquire_active_root(persist_root)

        from pathway_tpu.engine.probes import Prober
        from pathway_tpu.internals.config import get_config
        from pathway_tpu.internals.monitoring import MonitoringLevel, monitor_stats

        config = get_config()
        if monitoring_level is None:
            monitoring_level = MonitoringLevel.AUTO

        from pathway_tpu.engine.telemetry import Telemetry, TelemetryConfig
        from pathway_tpu.internals.license import License

        from pathway_tpu.engine import flight_recorder as _blackbox
        from pathway_tpu.engine import metrics as _registry

        license = License.new(config.license_key)
        registry = _registry.get_registry()
        telemetry = Telemetry(
            TelemetryConfig.create(
                license=license,
                run_id=config.run_id,
                monitoring_server=config.monitoring_server,
                trace_parent=trace_parent,
            ),
            lambda: result.prober.stats if result.prober is not None else None,
            # the unified registry (comm/persistence/supervisor/runner
            # instrumentation): scalars merge into every sample, histograms
            # export as OTLP histogram datapoints.  The commit-pipeline
            # gauges ride it too, through the collector PersistentStorage
            # registers — no extra_metrics wiring needed
            registry=registry,
        ).start()
        result.telemetry = telemetry

        # crash flight recorder: dump context for this worker — the ring
        # lands under <root>/blackbox/ on crash/fault, where the supervisor
        # gathers it into SupervisorResult.post_mortem
        from pathway_tpu.engine.faults import restart_attempt as _attempt
        from pathway_tpu.engine.persistence import writer_incarnation

        _blackbox.configure(
            worker=config.process_id,
            run_id=telemetry.config.run_id,
            trace_parent=trace_parent,
            attempt=_attempt(),
            # the dump path is fenced like every persistence-root write:
            # a zombie from a superseded incarnation must not drop its
            # stale ring into the live cluster's blackbox/
            incarnation=writer_incarnation(),
        )
        # hung-worker protocol, worker side: SIGUSR1 from the supervisor's
        # progress watchdog pulls the flight recorder out of a wedged
        # process BEFORE the SIGTERM/SIGKILL escalation destroys it.  The
        # distinct dump suffix keeps the hang story from clobbering (or
        # being clobbered by) this attempt's crash dump.  Main thread only
        # (signal.signal refuses elsewhere — e.g. Table.live() runs);
        # restored in the finally so embedding processes keep their own
        # handler after the run.
        import signal as _signal
        import threading as _threading

        if _threading.current_thread() is _threading.main_thread():
            # pathway-lint: context=signal
            def _usr1_dump(signum, frame):
                _blackbox.record(
                    "watchdog.sigusr1", worker=config.process_id,
                )
                _blackbox.get_recorder().dump(
                    "watchdog: epoch-progress deadline exceeded (SIGUSR1)",
                    suffix="watchdog",
                )

            try:
                prev_usr1 = _signal.signal(_signal.SIGUSR1, _usr1_dump)
                usr1_installed = True
            except (ValueError, OSError, AttributeError):
                prev_usr1 = None
        # the watchdog's on-disk liveness signal; a no-op without a
        # filesystem persistence root
        beacon = _ProgressBeacon(persist_root, config.process_id)
        # live-handoff participation (engine/autoscaler.py): worker 0
        # watches for the supervisor's handoff request at epoch
        # boundaries; every worker acks its fenced frontier through the
        # same sentinel.  Inert outside supervised runs (incarnation 0).
        handoff_sentinel = _HandoffSentinel(
            persist_root, config.process_id, config.processes
        )
        if handoff_sentinel.root is not None:
            # the autoscaler panel rides this worker's observability
            # surfaces: the supervisor maintains lease/autoscaler.json,
            # the worker re-exports it as autoscaler.* gauges (for
            # /status, /metrics, `pathway_tpu top`) and as the
            # flight-recorder dump's `autoscaler` payload section
            from pathway_tpu.engine import autoscaler as _autoscaler

            _as_root = handoff_sentinel.root
            registry.register_collector(
                "autoscaler.state",
                lambda: _autoscaler.state_metrics(_as_root),
            )
            _blackbox.get_recorder().set_autoscaler_supplier(
                lambda: _autoscaler.read_state_file(_as_root)
            )
            # warm-standby panel: apply-cursor beacons + promotion
            # history re-exported as standby.* / supervisor.promotions
            # gauges (the supervisor's own registry serves no /metrics)
            from pathway_tpu.engine import standby as _standby_mod

            registry.register_collector(
                "standby.state",
                lambda: _standby_mod.state_metrics(_as_root),
            )
            if worker_ctx is not None:
                # promotion sentinel: a PROMOTE request on the root means
                # a peer died and a standby is adopting its id — poison
                # the mesh so this worker unwinds through its drain-commit
                # and rejoins in-process (see run()), instead of waiting
                # out heartbeats on a peer that returns as a new process
                promote_watcher = _PromoteWatcher(
                    _as_root, config.process_id, worker_ctx.mesh
                ).start()
        # restart provenance, mesh-visible: the supervisor increments its
        # own supervisor.restarts counter, but that registry lives in the
        # spawn process, which serves no /metrics — each worker knows the
        # attempt that launched it, so the count is scrapeable here
        registry.gauge(
            "worker.restart.attempt",
            "supervisor restarts performed before this worker launch",
            worker=config.process_id,
        ).set(_attempt())
        # set (or clear) the dump root for THIS run: a run without a
        # filesystem persistence root must not dump into a previous run's
        _blackbox.get_recorder().root = persist_root
        _blackbox.record(
            "run.start", worker=config.process_id, attempt=_attempt(),
            workers=config.processes,
        )

        # performance observability (engine/profiler.py): per-operator
        # attribution sampled off the always-on step timers, JAX compile/
        # cache-miss accounting (the dynamic recompile-count==0 pin), and
        # a final profiler snapshot riding every flight-recorder dump so
        # post-mortems say where the time went
        from pathway_tpu.engine import profiler as _profiler

        profiler = _profiler.EpochProfiler()
        result.profiler = profiler
        if profiler.enabled:
            registry.register_collector(
                "profiler.operators", profiler.metrics_snapshot
            )
        _profiler.install_jax_accounting()
        _profiler.install_transfer_accounting()
        _blackbox.get_recorder().set_profile_supplier(
            lambda: profiler.crash_snapshot(scope)
        )

        # device observability (pathway_tpu/device/telemetry.py): every
        # flight-recorder dump carries the final DeviceExecutor snapshot
        # (cost/utilization/padding/HBM/queue) — post-mortems say what
        # the device was doing.  The supplier never instantiates an
        # executor: a run that never touched the device path dumps no
        # device section
        from pathway_tpu.device.executor import default_executor_snapshot

        _blackbox.get_recorder().set_device_supplier(
            default_executor_snapshot
        )

        # data-plane observability (engine/freshness.py): ingest-time
        # low-watermark propagation (per-output e2e latency + staleness)
        # and backlog.* backpressure attribution — the "where records
        # wait" complement of the profiler's "where CPU burns"
        from pathway_tpu.engine import freshness as _freshness

        freshness = _freshness.FreshnessTracker()
        result.freshness = freshness
        if freshness.enabled:
            freshness.attach(scope, lowerer.pollers)
            registry.register_collector(
                "freshness.tracker", freshness.metrics_snapshot
            )
            # post-mortems say what was STUCK, not just where time went:
            # every flight-recorder dump carries the final watermark/
            # backlog snapshot next to the profiler's attribution
            _blackbox.get_recorder().set_freshness_supplier(
                freshness.crash_snapshot
            )

        # serving observability (engine/serving.py): every flight-recorder
        # dump carries the admission controller's final snapshot (in-flight/
        # queue occupancy, degraded/draining, quarantine tail), and the
        # load shedder sees sustained *pipeline* pressure through the
        # freshness sensor — both inert when no REST route ever admits
        from pathway_tpu.engine import serving as _serving

        _blackbox.get_recorder().set_serving_supplier(
            _serving.snapshot_or_none
        )
        if freshness.enabled:
            _serving.set_pressure_supplier(freshness.worst_staleness)

        # request tracing + SLOs (engine/tracing.py, engine/slo.py):
        # request spans ride this run's bounded telemetry export queue,
        # the declared-SLO evaluator joins the scrape path, and every
        # flight-recorder dump carries the finished-request ring
        # (waterfalls) and the SLO burn/budget snapshot
        from pathway_tpu.engine import slo as _slo
        from pathway_tpu.engine import tracing as _tracing

        _tracing.set_exporter(telemetry)
        _slo.install(registry)
        _blackbox.get_recorder().set_tracing_supplier(_tracing.snapshot)
        _blackbox.get_recorder().set_slo_supplier(
            lambda: _slo.get_evaluator().snapshot()
        )

        if with_http_server:
            from pathway_tpu.engine.http_server import MonitoringServer

            http_server = MonitoringServer(
                process_id=config.process_id,
                port=config.monitoring_http_port,
                run_id=config.run_id,
            ).start()
        with monitor_stats(monitoring_level) as monitor:
            prober = Prober(scope, pollers=lowerer.pollers)
            if monitor is not None:
                prober.callbacks.append(monitor.update)
            if http_server is not None:
                prober.callbacks.append(http_server.update)
            result.prober = prober
            # dataflow progress totals join the unified registry (the
            # WeakMethod registration dies with the prober; each run
            # replaces the previous run's collector under this name)
            registry.register_collector(
                "dataflow.prober", prober.metrics_snapshot
            )
            with telemetry.span("pathway.run", workers=config.threads):
                try:
                    _event_loop(
                        scope, lowerer, result, max_epochs=max_epochs,
                        storage=storage, prober=prober, telemetry=telemetry,
                        beacon=beacon,
                        # None when disabled, so the default configuration
                        # pays zero per-epoch cost (not even the call)
                        profiler=profiler if profiler.enabled else None,
                        freshness=freshness if freshness.enabled else None,
                        handoff=handoff_sentinel,
                    )
                except BaseException as exc:
                    from pathway_tpu.engine.comm import MeshPoisoned

                    if isinstance(exc, MeshPoisoned):
                        # promotion rejoin, not a failure: run() acks and
                        # re-enters after the finally's drain-commit.  No
                        # crash dump — the blackbox ring stays for real
                        # failures.  In-flight serving requests wait on
                        # epochs this mesh will never run: answer them
                        # with the typed retry signal now instead of
                        # letting them time out across the rejoin.
                        _blackbox.record(
                            "promotion.rejoin", worker=config.process_id,
                            reason=str(exc),
                        )
                        _serving.fail_inflight_for_promotion()
                    else:
                        # black-box the failure BEFORE unwinding: the
                        # ring's last events are the crash story the
                        # supervisor (or `pathway_tpu blackbox`) reads
                        # back post-mortem
                        _blackbox.record(
                            "run.failed", worker=config.process_id,
                            error=repr(exc),
                        )
                        _blackbox.dump(f"run failed: {exc!r}")
                    # failure hooks: exported tables must flip to failed so
                    # concurrent importers raise instead of waiting forever
                    # (the scopeguard of dataflow/export.rs:143-146)
                    for node in scope.nodes:
                        abort = getattr(node, "on_abort", None)
                        if abort is not None:
                            abort()
                    raise
    finally:
        if usr1_installed:
            import signal as _signal

            try:
                _signal.signal(
                    _signal.SIGUSR1,
                    prev_usr1 if prev_usr1 is not None else _signal.SIG_DFL,
                )
            except (ValueError, OSError):
                pass
        if result.profiler is not None:
            # the run's profile outlives the run: final snapshot to the
            # PATHWAY_PROFILE_OUTPUT path (best-effort), and the crash
            # supplier cleared so the recorder stops referencing this
            # run's node arena
            from pathway_tpu.engine import flight_recorder as _blackbox

            if result.profiler.enabled:
                result.profiler.sample(scope, result.epochs)
                result.profiler.write_output()
            _blackbox.get_recorder().set_profile_supplier(None)
        if result.freshness is not None:
            # same lifetime rule for the freshness supplier: the recorder
            # must not outlive this run's pollers and node arena
            from pathway_tpu.engine import flight_recorder as _blackbox

            _blackbox.get_recorder().set_freshness_supplier(None)
        # the device supplier references only the process-global executor
        # (no run state), but clearing it keeps the recorder's lifetime
        # contract uniform across all three suppliers
        from pathway_tpu.engine import flight_recorder as _blackbox_dev

        _blackbox_dev.get_recorder().set_device_supplier(None)
        _blackbox_dev.get_recorder().set_autoscaler_supplier(None)
        _blackbox_dev.get_recorder().set_serving_supplier(None)
        _blackbox_dev.get_recorder().set_tracing_supplier(None)
        _blackbox_dev.get_recorder().set_slo_supplier(None)
        # ...and the serving shedder must stop referencing this run's
        # freshness tracker (same lifetime rule as the suppliers above)
        from pathway_tpu.engine import serving as _serving_cleanup

        _serving_cleanup.set_pressure_supplier(None)
        # the trace exporter holds this run's Telemetry: clear it before
        # telemetry.close() so no late span enqueues into a closed queue
        from pathway_tpu.engine import tracing as _tracing_cleanup

        _tracing_cleanup.set_exporter(None)
        if promote_watcher is not None:
            promote_watcher.stop()
        if worker_ctx is not None:
            worker_ctx.close()
        if result.telemetry is not None:
            result.telemetry.close()
        if http_server is not None:
            http_server.close()
        try:
            if storage is not None:
                # also on interrupt/error: commit whatever frontier is
                # consistent.  Offsets never advance past the last PROCESSED
                # epoch (rows staged for later epochs are not yet in any
                # snapshot), and a failure mid-epoch must not dump
                # half-stepped operator state — the previous consistent
                # generation stays committed instead.  This final commit()
                # is the shutdown DRAIN of the async pipeline: it publishes
                # every staged generation in order, barriers on in-flight
                # chunk writes, and only then commits the final frontier —
                # so a clean finish commits exactly the flushed frontier.
                frontier = (
                    result.last_time if result.last_time is not None else -1
                )
                if result.epoch_failed and storage.operator_persistence:
                    import logging

                    logging.getLogger("pathway_tpu").warning(
                        "run failed mid-epoch; keeping the previous "
                        "consistent operator snapshot generation"
                    )
                else:
                    # the shutdown drain-commit gets its own span so the
                    # run's trace shows where final durability time went
                    commit_span = (
                        result.telemetry.span("pathway.commit", final=True)
                        if result.telemetry is not None
                        else _nullcontext()
                    )
                    with commit_span:
                        storage.commit(
                            processed_up_to=frontier,
                            full_operator_dump=result.clean_finish,
                        )
                    # this drain-commit durably covers every drained commit
                    # marker (their chunks were flushed at drain), so
                    # release the tail acks the in-loop published_seq
                    # gating may still be holding — snapshots staged but
                    # not yet published when the loop exited
                    _ack_sources(lowerer.pollers, persisted=True)
        finally:
            # the final commit may raise (failing store): the process-global
            # UDF-cache root and the connector cleanups must be released
            # regardless, or the leaked root poisons every later run in this
            # process (e.g. persistence-derived sink key salts)
            if storage is not None:
                from pathway_tpu.engine import persistence as pz

                pz.release_active_root(root_token)
            for cleanup in lowerer.cleanups:
                try:
                    cleanup()
                except Exception:
                    pass
    return result


def _topology_handshake(persistence_config: Any, cfg: Any) -> None:
    """Verify this worker's launch topology against the lease on its
    persistence root (supervised runs only — the supervisor records the
    target worker count in the incarnation lease before every launch).

    The mesh is sized from ``PATHWAY_PROCESSES``; this check makes the
    LEASE the authority: a mismatch means the supervisor and the worker
    disagree about the cluster shape, and resuming would mis-shard every
    exchanged row.  Read-only — a missing root, missing lease, or a lease
    without a recorded topology (pre-rescale roots) passes silently.
    """
    from pathway_tpu.engine.persistence import (
        read_lease_file,
        writer_incarnation,
    )

    if writer_incarnation() <= 0:
        return  # unsupervised: no lease authority to handshake with
    root = None
    backend_cfg = getattr(persistence_config, "backend", None)
    if backend_cfg is not None:
        if getattr(backend_cfg, "kind", None) == "filesystem":
            root = getattr(backend_cfg, "path", None)
    elif persistence_config is None and cfg.replay_storage:
        root = cfg.replay_storage
    if not root or not os.path.isdir(root):
        return
    lease = read_lease_file(root)
    if lease is None:
        return
    workers = lease.get("workers")
    if not isinstance(workers, int):
        return
    if workers != cfg.processes:
        raise RuntimeError(
            f"topology handshake failed: the lease on {root} records a "
            f"cluster of {workers} worker(s) (incarnation "
            f"{lease['incarnation']}), but this worker was launched with "
            f"PATHWAY_PROCESSES={cfg.processes} — the supervisor and the "
            "worker disagree about the mesh size. Relaunch through "
            f"`pathway_tpu spawn --supervise -n {workers}`, or rescale "
            "deliberately by re-running the supervisor at the new count."
        )
    if cfg.process_id >= workers:
        raise RuntimeError(
            f"topology handshake failed: worker id {cfg.process_id} is "
            f"outside the leased topology of {workers} worker(s) on {root}"
        )


def _persistence_root(persistence_config: Any) -> str | None:
    """This run's filesystem persistence root, or None — the same backend
    unwrap ``_topology_handshake`` performs, shared by the standby branch
    and the promotion-rejoin loop of :func:`run`."""
    from pathway_tpu.internals.config import get_config

    backend_cfg = getattr(persistence_config, "backend", None)
    if backend_cfg is not None:
        if getattr(backend_cfg, "kind", None) == "filesystem":
            return getattr(backend_cfg, "path", None) or None
        return None
    if persistence_config is None:
        return get_config().replay_storage or None
    return None


# promotion seqs this process already acked: the promote sentinel of the
# NEXT mesh (post-rejoin) must not re-poison on the still-present PROMOTE
# file while the supervisor collects the remaining acks
_ACKED_PROMOTE_SEQS: set[int] = set()


def _pending_promotion(persistence_config: Any) -> dict | None:
    """The PROMOTE request this worker still owes a rejoin, or None."""
    from pathway_tpu.engine import persistence as pz
    from pathway_tpu.internals.config import get_config

    root = _persistence_root(persistence_config)
    if root is None or pz.writer_incarnation() <= 0:
        return None
    req = pz.read_promote_request(root)
    if (
        req is None
        or req["incarnation"] != pz.writer_incarnation()
        or req["worker"] == get_config().process_id
        or req["seq"] in _ACKED_PROMOTE_SEQS
    ):
        return None
    return req


def _promotion_rejoin(persistence_config: Any) -> None:
    """Between a poisoned ``_run_once`` and its re-entry: ack the PROMOTE
    request (the drain-commit already ran in ``_run_once``'s finally, so
    the ack certifies this worker's frontier is durable and its old mesh
    is gone) and re-open the admission controller the unwind drained."""
    from pathway_tpu.engine import persistence as pz
    from pathway_tpu.engine import serving as _serving
    from pathway_tpu.internals.config import get_config

    req = _pending_promotion(persistence_config)
    if req is not None:
        root = _persistence_root(persistence_config)
        pz.write_promote_ack(
            root,
            get_config().process_id,
            seq=req["seq"],
            worker=req["worker"],
            incarnation=req["incarnation"],
        )
        _ACKED_PROMOTE_SEQS.add(req["seq"])
    _serving.resume_after_promotion()


def _make_storage(persistence_config: Any):
    """Build engine PersistentStorage from a ``pw.persistence.Config``, or
    from the record/replay env config (``PATHWAY_REPLAY_STORAGE`` +
    ``PATHWAY_SNAPSHOT_ACCESS``, reference ``internals/config.py:35-54``)
    when no explicit config is given."""
    from pathway_tpu.internals.config import get_config

    if persistence_config is None:
        cfg = get_config()
        if not cfg.replay_storage:
            return None
        from pathway_tpu.engine import persistence as pz

        storage = pz.PersistentStorage(
            _flaky_wrap(pz.FileBackend(cfg.replay_storage)),
            snapshot_interval_ms=0,
            worker=cfg.process_id,
        )
        storage.snapshot_access = _normalize_access(cfg.snapshot_access)
        storage.continue_after_replay = cfg.continue_after_replay
        return storage
    backend_cfg = getattr(persistence_config, "backend", None)
    if backend_cfg is None:
        return None
    from pathway_tpu.engine import persistence as pz

    backend = _flaky_wrap(pz.backend_from_config(backend_cfg))
    storage = pz.PersistentStorage(
        backend,
        snapshot_interval_ms=getattr(persistence_config, "snapshot_interval_ms", 0),
        mode=getattr(persistence_config, "persistence_mode", None),
        # worker-sharded snapshots: each process owns metadata.json.<id> and
        # snapshots/<id>/... — without this, multi-process runs clobber one
        # another's state (the reference shards snapshot files per worker)
        worker=get_config().process_id,
    )
    storage.snapshot_access = _normalize_access(
        getattr(persistence_config, "snapshot_access", None)
    )
    storage.continue_after_replay = getattr(
        persistence_config, "continue_after_replay", True
    )
    return storage


def _flaky_wrap(backend: Any) -> Any:
    """Blob-level fault injection (PATHWAY_FAULT_PLAN blob_* specs):
    chaos/soak runs exercise checkpoint commit failure paths with no code
    change — a no-op wrapper selection when no plan is active."""
    from pathway_tpu.engine import faults as _faults

    return _faults.wrap_backend(backend)


def _normalize_access(access: Any) -> str | None:
    """"record"/"replay" as lowercase strings, whether given as str or enum."""
    if access is None or isinstance(access, str):
        return access.lower() if isinstance(access, str) else None
    return str(getattr(access, "name", access)).lower()


def run_all(**kwargs: Any) -> RunResult:
    return run(**kwargs)


def _input_nodes(scope: df.Scope) -> list[df.InputNode]:
    return [n for n in scope.nodes if isinstance(n, df.InputNode)]


def _ack_sources(
    pollers,
    *,
    persisted: bool,
    up_to_time: int | None = None,
    marker_frontiers: dict | None = None,
) -> None:
    """Tell external-offset sources (Kafka groups) a durability point passed.

    ``persisted=True``: called when ``storage.published_seq`` advances —
    a staged snapshot became durable (its generation manifest published,
    or a confirmed no-op) — and acks pollers whose rows land in input
    snapshots (replay covers them), gated on ``marker_frontiers`` (the
    per-poller drained-marker frontier captured when that snapshot was
    STAGED): markers drained while the publish was in flight belong to a
    later snapshot and must not be acked by this one.
    ``persisted=False``: called after an epoch ran — acks pollers with no
    snapshot state, gated on the epoch time.
    """
    for poller in pollers:
        ack = getattr(poller, "ack_processed", None)
        if ack is None:
            continue
        has_snapshots = getattr(poller, "persist_state", None) is not None
        if has_snapshots != persisted:
            continue
        if persisted and marker_frontiers is not None:
            ack(up_to_marker=marker_frontiers.get(id(poller)))
        else:
            ack(up_to_time)


def _marker_frontiers(pollers) -> dict:
    """{id(poller): drained-marker frontier} for persisted pollers, taken
    at snapshot-STAGING time — what the staged snapshot actually covers."""
    out: dict = {}
    for poller in pollers:
        frontier = getattr(poller, "marker_frontier", None)
        if frontier is not None and getattr(poller, "persist_state", None) is not None:
            out[id(poller)] = frontier()
    return out


def _attach_wake(pollers) -> "Any":
    """Per-run wake signal: reader threads set it on enqueue so the idle
    park ends immediately (per-run, NOT process-wide — a shared event
    would busy-spin one run's loop while another run streams)."""
    import threading as _threading

    wake = _threading.Event()
    for p in pollers:
        q = getattr(p, "q", None)
        if q is not None and hasattr(q, "wake"):
            q.wake = wake
    return wake


class _ProgressBeacon:
    """Epoch-loop liveness beacon for the supervisor's hung-worker watchdog.

    The epoch loop touches ``<root>/lease/progress.<worker>`` — on every
    processed epoch AND on idle iterations — so the beacon's mtime means
    "the event loop is alive and scheduling", not "input is flowing": an
    idle-but-healthy stream keeps touching, a deadlocked epoch loop or a
    wedged commit drain stops.  Rate-limited to one write per 0.25 s; the
    write is a tiny pid overwrite, so the steady-state cost is four small
    writes per second.  A run without a filesystem persistence root has no
    beacon (and the supervisor has no watchdog for it), and so does an
    UNSUPERVISED run — nothing would ever read the beacon, and a solo
    run's root should not grow a ``lease/`` directory no lease owns.
    """

    _MIN_INTERVAL_S = 0.25

    def __init__(self, root: str | None, worker: int):
        # supervised is recognizable from the worker side: the supervisor
        # exports PATHWAY_INCARNATION with the lease, and an env-configured
        # watchdog leaves PATHWAY_EPOCH_DEADLINE_S visible here too
        if root is not None:
            from pathway_tpu.engine.persistence import writer_incarnation
            from pathway_tpu.engine.supervisor import ENV_EPOCH_DEADLINE
            from pathway_tpu.internals.config import env_raw

            if writer_incarnation() <= 0 and not env_raw(ENV_EPOCH_DEADLINE):
                root = None
        self.path = (
            os.path.join(root, "lease", f"progress.{worker}")
            if root
            else None
        )
        self._last = 0.0
        if self.path is not None:
            try:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
            except OSError:
                self.path = None
        # load beacon (engine/autoscaler.py): beside liveness, a
        # supervised worker reports its load reading (worst output
        # staleness + backlog) at the same rate-limited cadence — the
        # sensor feed of the supervisor's scale controller.  Solo and
        # autoscaling-off runs pay nothing, not even the supplier call.
        self.root = root if self.path is not None else None
        self.worker = worker
        self._last_load = 0.0
        if self.root is not None:
            from pathway_tpu.engine.autoscaler import autoscale_enabled

            self._load_enabled = autoscale_enabled()
        else:
            self._load_enabled = False
        self.touch(force=True)

    def touch(self, force: bool = False) -> None:
        if self.path is None:
            return
        now = _time.monotonic()
        if not force and now - self._last < self._MIN_INTERVAL_S:
            return
        self._last = now
        try:
            with open(self.path, "w") as f:
                f.write(str(os.getpid()))
        except OSError:
            pass  # liveness reporting must never take the worker down

    _LOAD_INTERVAL_S = 0.5

    def report_load(self, supplier) -> None:
        """Rate-limited load beacon write; ``supplier`` returns
        ``(worst_staleness_s, backlog, epochs)`` and is only invoked when
        a write is actually due (so the snapshot cost is paid at beacon
        cadence, not per loop iteration)."""
        if not self._load_enabled:
            return
        now = _time.monotonic()
        if now - self._last_load < self._LOAD_INTERVAL_S:
            return
        self._last_load = now
        from pathway_tpu.engine.autoscaler import write_load_beacon

        try:
            staleness_s, backlog, epochs = supplier()
            write_load_beacon(
                self.root, self.worker,
                staleness_s=staleness_s, backlog=backlog, epochs=epochs,
            )
        except Exception:  # noqa: BLE001 - load reporting must never
            pass  # take the worker down (same rule as touch())


def _load_reading(freshness, result) -> tuple[float, float, int]:
    """One (worst staleness, backlog, epochs) sensor reading for the load
    beacon.  Backlog sums the row/queue-count families of the freshness
    tracker's backlog attribution (ages excluded — mixing seconds into a
    count would double-weight a stall the staleness number already
    carries).  No tracker → (0, 0): an instrumentation gap reads as calm,
    never as load."""
    staleness = 0.0
    backlog = 0.0
    if freshness is not None:
        staleness = freshness.worst_staleness() or 0.0
        for key, value in freshness.metrics_snapshot().items():
            if key.startswith(
                (
                    "backlog.ingest.rows",
                    "backlog.connector.queue",
                    "backlog.epochs.pending",
                )
            ):
                backlog += value
    return staleness, backlog, result.epochs


class _HandoffSentinel:
    """Worker-side watch for the supervisor's live-handoff request.

    Worker 0 polls ``lease/HANDOFF`` (rate-limited file read) at epoch
    boundaries and, on a valid request for THIS incarnation and a
    DIFFERENT worker count, returns the target so the epoch loop can
    broadcast the handoff decision.  Requests from other incarnations
    (zombie roots, stale files a crashed supervisor left behind) are
    ignored — the supervisor clears the files either way."""

    _MIN_INTERVAL_S = 0.2

    def __init__(self, root: str | None, worker: int, workers: int):
        from pathway_tpu.engine.persistence import writer_incarnation

        self.incarnation = writer_incarnation()
        self.root = root if self.incarnation > 0 else None
        self.worker = worker
        self.workers = workers
        self._last = 0.0

    def poll(self) -> int | None:
        """The pending handoff target (worker count), or None."""
        if self.root is None:
            return None
        now = _time.monotonic()
        if now - self._last < self._MIN_INTERVAL_S:
            return None
        self._last = now
        from pathway_tpu.engine.persistence import read_handoff_request

        req = read_handoff_request(self.root)
        if (
            req is None
            or req["incarnation"] != self.incarnation
            or req["to_workers"] == self.workers
        ):
            return None
        return req["to_workers"]

    def ack(self, to_workers: int, frontier: int) -> None:
        if self.root is None:
            return
        from pathway_tpu.engine.persistence import write_handoff_ack

        write_handoff_ack(
            self.root, self.worker,
            incarnation=self.incarnation, to_workers=to_workers,
            frontier=frontier,
        )


class _PromoteWatcher:
    """Background watch for the supervisor's PROMOTE request.

    A promotion must interrupt survivors that are BLOCKED inside mesh
    collectives (worker 0 gathering from the dead peer, everyone else
    waiting on the epoch-go broadcast) — the epoch-boundary polling the
    handoff sentinel uses can never fire there.  So this tiny daemon
    thread polls ``lease/PROMOTE`` and, on a valid request for another
    worker of THIS incarnation that this process has not already acked,
    poisons the mesh: every blocked collective raises
    :class:`~pathway_tpu.engine.comm.MeshPoisoned`, the run unwinds
    through its consistent drain-commit, and ``run()`` rejoins
    in-process.  One-shot per mesh lifetime."""

    _POLL_S = 0.05

    def __init__(self, root: str, worker: int, mesh: Any):
        self.root = root
        self.worker = worker
        self.mesh = mesh
        import threading as _threading

        self._stop = _threading.Event()
        self._thread = _threading.Thread(
            target=self._watch, name=f"promote-watch-{worker}", daemon=True
        )

    def start(self) -> "_PromoteWatcher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    # pathway-lint: context=promote-watch
    def _watch(self) -> None:
        from pathway_tpu.engine import persistence as pz

        incarnation = pz.writer_incarnation()
        while not self._stop.wait(self._POLL_S):
            try:
                req = pz.read_promote_request(self.root)
            except OSError:
                continue
            if (
                req is None
                or req["incarnation"] != incarnation
                or req["worker"] == self.worker
                or req["seq"] in _ACKED_PROMOTE_SEQS
            ):
                continue
            self.mesh.poison(
                f"promotion {req['seq']}: standby {req['standby']} is "
                f"adopting worker {req['worker']}"
            )
            return


def _handoff_exit(
    result,
    storage,
    sentinel,
    to_n: int,
    frontier: int,
    mesh=None,
) -> None:
    """The worker's half of a live handoff: drain-commit the EXACT
    current frontier (stamped ``handoff_to``), fence the storage so
    nothing later can move it, barrier with every peer (all-or-nothing —
    one dead peer fails the collective and the supervisor falls back),
    then ack and let the epoch loop break WITHOUT finishing the scope.

    The injected ``handoff_crash`` fault (SIGKILL after the fence commit,
    before the ack) lands between the commit and the barrier: exactly the
    window where a real mid-handoff death leaves a fenced-but-unacked
    root the restart fallback must absorb."""
    from pathway_tpu.engine import faults as _faults
    from pathway_tpu.engine import flight_recorder as _blackbox

    _blackbox.record(
        "handoff.begin", worker=sentinel.worker, to_workers=to_n,
        frontier=frontier,
    )
    if storage is not None:
        storage.fence_for_handoff(to_n)
        # synchronous drain: publishes every staged async generation in
        # order, then the handoff generation itself — the manifest the
        # successor topology's repartition replay reads
        storage.commit(processed_up_to=frontier)
    _faults.maybe_crash_handoff(worker=sentinel.worker, to_workers=to_n)
    if mesh is not None:
        # retire FIRST: peer departures during the barrier (and after it,
        # as everyone tears down) are the expected sound of a coordinated
        # exit, not a partition — but a peer that DIED mid-handoff still
        # fails the barrier with CommError, which is the point: the
        # handoff is all-or-nothing and the supervisor falls back
        mesh.retire()
        mesh.barrier(("handoff", to_n))
    sentinel.ack(to_n, frontier)
    result.handoff_to = to_n
    _blackbox.record(
        "handoff.acked", worker=sentinel.worker, to_workers=to_n,
    )


def _epoch_instruments():
    """(histogram, recorder) pair the epoch loops stamp each epoch with:
    a registry histogram of epoch wall time and the flight-recorder ring
    (both bounded-cost; see engine/metrics.py, engine/flight_recorder.py)."""
    from pathway_tpu.engine import flight_recorder as _blackbox
    from pathway_tpu.engine import metrics as _registry

    hist = _registry.get_registry().histogram(
        "epoch.duration.ms", "wall time of one processed epoch (ms)",
        buckets=_registry.MS_BUCKETS,
    )
    return hist, _blackbox


# pathway-lint: context=epoch
def _event_loop(
    scope: df.Scope,
    lowerer: Lowerer,
    result: RunResult,
    max_epochs: int | None = None,
    storage: Any = None,
    prober: Any = None,
    telemetry: Any = None,
    beacon: Any = None,
    profiler: Any = None,
    freshness: Any = None,
    handoff: Any = None,
) -> None:
    if scope.worker is not None:
        return _event_loop_coordinated(
            scope, lowerer, result, max_epochs=max_epochs, storage=storage,
            prober=prober, telemetry=telemetry, beacon=beacon,
            profiler=profiler, freshness=freshness, handoff=handoff,
        )
    if beacon is None:
        beacon = _ProgressBeacon(None, 0)
    epoch_hist, blackbox = _epoch_instruments()
    inputs = _input_nodes(scope)
    pollers = lowerer.pollers
    wake = _attach_wake(pollers)
    last_time = -1
    drain_spins = 0  # consecutive idle drain epochs (quiesce guard)
    # snapshot_interval_ms=0 means "as often as possible" (reference
    # persistence/__init__.py:95-101); commit() no-ops when nothing advanced
    snapshot_interval = (
        (storage.snapshot_interval_ms / 1000.0) if storage is not None else None
    )
    last_snapshot = _time.monotonic()
    # (staged durability seq, marker frontiers at staging) awaiting publish
    pending_acks: deque = deque()
    while True:
        # liveness beacon: touched on EVERY loop iteration (idle included),
        # so its mtime proves the event loop schedules — a wedged epoch or
        # a deadlock stops it and the supervisor's watchdog takes over
        beacon.touch()
        beacon.report_load(lambda: _load_reading(freshness, result))
        if handoff is not None:
            to_n = handoff.poll()
            if to_n is not None:
                from pathway_tpu.engine import serving as _serving

                # serving drain gates the rescale: the first sighting of
                # the handoff request stop-accepts (new requests get 503)
                # and the epoch loop KEEPS running so in-flight requests
                # complete — the sentinel re-returns to_n every poll, so
                # the fence fires on the first boundary where every
                # admitted request is answered (or the drain budget
                # lapses).  Zero in-flight HTTP requests are dropped.
                if _serving.ready_for_handoff():
                    # planned rescale (single supervised worker: the grow
                    # from 1 starts here too): drain, fence, ack, exit 0
                    _handoff_exit(result, storage, handoff, to_n, last_time)
                    break
        if (
            storage is not None
            and (_time.monotonic() - last_snapshot) >= snapshot_interval
        ):
            # non-blocking commit: chunk framing/hash/upload and the
            # manifest barrier run on the persistence writer pool while
            # this loop keeps computing epochs (engine/persistence.py);
            # the run's final commit (run()'s finally) drains the pipeline
            staged = storage.commit_async(processed_up_to=last_time)
            pending_acks.append((staged, _marker_frontiers(pollers)))
            last_snapshot = _time.monotonic()
        while (
            storage is not None
            and pending_acks
            and storage.published_seq >= pending_acks[0][0]
        ):
            # a staged snapshot became DURABLE (its generation manifest
            # published, or a confirmed no-op): sources whose rows are in
            # it may now commit their broker offsets — only up to the
            # marker frontier captured when it was staged, and never on
            # commit_async returning, which precedes durability
            _seq, frontiers = pending_acks.popleft()
            _ack_sources(pollers, persisted=True, marker_frontiers=frontiers)
        exhausted = True
        for poller in pollers:
            if not poller.poll():
                exhausted = False
        # choose the next epoch: smallest staged time across inputs
        times: set[int] = set()
        for inp in inputs:
            times.update(inp.pending_times())
        if times:
            t = min(times)
            if t <= last_time:
                t = last_time + 2  # keep times strictly increasing & even
            for inp in inputs:
                # merge any earlier-stamped staged rows into this epoch
                inp.merge_staged_through(t)
                inp.emit_time(t)
            result.epoch_failed = True
            t0 = _time.perf_counter()
            span = (
                telemetry.epoch_span(t, result.epochs)
                if telemetry is not None
                else _nullcontext()
            )
            with span:
                scope.run_epoch(t)
            epoch_hist.observe((_time.perf_counter() - t0) * 1000.0)
            blackbox.record("epoch", time=t, index=result.epochs)
            result.epoch_failed = False
            drain_spins = 0
            last_time = t
            result.last_time = t
            result.epochs += 1
            if profiler is not None:
                # cadence-gated top-N attribution off the per-node step
                # timers run_epoch already maintains (engine/profiler.py)
                profiler.on_epoch(scope, result.epochs)
            if freshness is not None:
                # propagate the ingest low-watermark frontier and record
                # per-output delivery latency (engine/freshness.py)
                freshness.after_epoch(scope)
            # sources without input snapshots (no persistence, or UDF-cache-
            # only mode): the processed epoch is their durability boundary —
            # broker offsets may cover rows up to it, and no further
            _ack_sources(pollers, persisted=False, up_to_time=t)
            if prober is not None and prober.callbacks:
                prober.update(epochs=result.epochs)
            if max_epochs is not None and result.epochs >= max_epochs:
                break
            continue
        all_finished = exhausted and all(inp.finished for inp in inputs)
        if all_finished:
            break
        # epoch-boundary hooks (error-log drains, buffer releases) may have
        # parked deltas in node pending queues; an idle stream must still
        # deliver them to subscribers rather than wait for the next input
        if any(n.has_pending() for n in scope.nodes):
            drain_spins += 1
            if drain_spins > 1000:
                raise df.EngineError(
                    "idle drain did not quiesce: a node re-parks deltas "
                    "every epoch (same condition finish() guards against)"
                )
            last_time += 2
            result.epoch_failed = True
            scope.run_epoch(last_time)
            result.epoch_failed = False
            result.last_time = last_time
            continue
        # idle streams still drain commit markers: a Kafka source's
        # timer-driven COMMITs keep arriving with no new epochs, and the
        # offsets for the last processed epoch must still reach the broker
        _ack_sources(pollers, persisted=False, up_to_time=last_time)
        # park until a reader signals new data (or the 1 ms cap): serving
        # queries wake the loop immediately instead of riding out the park
        wake.wait(0.001)
        wake.clear()
    scope.current_time = max(scope.current_time, last_time)
    if result.handoff_to is not None:
        # live handoff: the scope is NOT finished — no on_finish hooks, no
        # final flush; the run continues at the new topology from the
        # fenced frontier, and finishing here would emit end-of-stream
        # effects the successor would then replay on top of
        return
    scope.finish()
    result.clean_finish = True
    if prober is not None:
        prober.update(done=True, epochs=result.epochs)


# pathway-lint: context=epoch
def _event_loop_coordinated(
    scope: df.Scope,
    lowerer: Lowerer,
    result: RunResult,
    max_epochs: int | None = None,
    storage: Any = None,
    prober: Any = None,
    telemetry: Any = None,
    beacon: Any = None,
    profiler: Any = None,
    freshness: Any = None,
    handoff: Any = None,
) -> None:
    """Multi-worker BSP loop: worker 0 sequences epochs, every worker runs
    them in lockstep, exchanging rows at the declared exchange points.

    Mirrors the single-process loop; the extra steps are (a) epoch
    negotiation (the progress-gossip analog of timely frontiers over the
    cluster, SURVEY.md §2b) and (b) the post-ingest exchange that routes
    each staged row to the worker owning its key shard (dataflow.rs:1414).
    """
    ctx = scope.worker
    mesh = ctx.mesh
    if beacon is None:
        beacon = _ProgressBeacon(None, 0)
    epoch_hist, blackbox = _epoch_instruments()
    inputs = _input_nodes(scope)
    pollers = lowerer.pollers
    wake = _attach_wake(pollers)
    last_time = -1
    drain_spins = 0
    round_ = 0
    snapshot_interval = (
        (storage.snapshot_interval_ms / 1000.0) if storage is not None else None
    )
    last_snapshot = _time.monotonic()
    pending_acks: deque = deque()  # (staged seq, marker frontiers)
    while True:
        # event-loop liveness for the supervisor's watchdog (idle included)
        beacon.touch()
        beacon.report_load(lambda: _load_reading(freshness, result))
        if (
            storage is not None
            and (_time.monotonic() - last_snapshot) >= snapshot_interval
        ):
            # non-blocking: durability I/O overlaps the BSP epoch rounds
            staged = storage.commit_async(processed_up_to=last_time)
            pending_acks.append((staged, _marker_frontiers(pollers)))
            last_snapshot = _time.monotonic()
        while (
            storage is not None
            and pending_acks
            and storage.published_seq >= pending_acks[0][0]
        ):
            # broker offsets ack only once the staged snapshot is durable,
            # and only up to the marker frontier captured at staging
            _seq, frontiers = pending_acks.popleft()
            _ack_sources(pollers, persisted=True, marker_frontiers=frontiers)
        exhausted = True
        for poller in pollers:
            if not poller.poll():
                exhausted = False
        times: set[int] = set()
        for inp in inputs:
            times.update(inp.pending_times())
        local_min = min(times) if times else None
        all_finished = exhausted and all(inp.finished for inp in inputs)

        local_pending = any(n.has_pending() for n in scope.nodes)
        round_ += 1
        # the epoch-negotiation gather doubles as the mesh-wide freshness
        # aggregation path: each worker ships its worst output staleness,
        # worker 0 publishes the cluster maximum (one gauge, zero extra
        # collectives — the PR-4 trace-broadcast pattern)
        local_stale = (
            freshness.worst_staleness() if freshness is not None else None
        )
        gathered = mesh.gather(
            ("epoch", round_),
            (local_min, all_finished, local_pending, local_stale),
        )
        if mesh.worker_id == 0:
            if freshness is not None:
                freshness.record_mesh_staleness(
                    [s for _m, _f, _p, s in gathered]
                )
            mins = [m for m, _f, _p, _s in gathered if m is not None]
            handoff_to = handoff.poll() if handoff is not None else None
            if handoff_to is not None:
                from pathway_tpu.engine import serving as _serving

                if not _serving.ready_for_handoff():
                    # serving drain in progress (worker 0 owns the REST
                    # ingress): stop-accept has begun, but in-flight
                    # requests still need epochs — defer the rescale
                    # decision; the sentinel re-returns to_n next round
                    handoff_to = None
            if handoff_to is not None:
                # planned rescale outranks everything: the fenced
                # frontier must be THIS epoch boundary, before any more
                # input folds in
                decision = ("handoff", handoff_to)
            elif mins:
                t = min(mins)
                if t <= last_time:
                    t = last_time + 2  # strictly increasing, even
                decision = ("epoch", t)
            elif any(p for _m, _f, p, _s in gathered):
                # boundary-produced deltas (error logs, buffer releases)
                # drain in lockstep on every worker
                drain_spins += 1
                if drain_spins > 1000:
                    decision = ("stop", None)  # non-quiescing node; bail
                else:
                    decision = ("drain", last_time + 2)
            elif all(fin for _m, fin, _p, _s in gathered):
                decision = ("stop", None)
            else:
                decision = ("idle", None)
        else:
            decision = None
        kind, t = mesh.bcast(("epoch-go", round_), decision)

        if kind == "handoff":
            # every worker exits through the coordinated drain: commit
            # the exact frontier (stamped handoff_to), fence, barrier
            # (all-or-nothing), ack, and leave the loop WITHOUT finishing
            # the scope — the supervisor relaunches at the new topology
            _handoff_exit(
                result, storage, handoff, t, last_time, mesh=mesh
            )
            break
        if kind == "stop":
            break
        if kind == "drain":
            # boundary-delta drain: run the epoch but do NOT reset the
            # quiesce counter (only real input epochs prove progress)
            result.epoch_failed = True
            scope.run_epoch(t)
            result.epoch_failed = False
            last_time = t
            result.last_time = t
            continue
        if kind == "idle":
            _ack_sources(pollers, persisted=False, up_to_time=last_time)
            wake.wait(0.001)
            wake.clear()
            continue
        for inp in inputs:
            inp.merge_staged_through(t)
        # route each staged row to the worker owning its key shard; a
        # non-partitioned source read on worker 0 scatters here
        for inp in inputs:
            staged = inp.take_staged(t, [])
            merged = ctx.exchange_deltas(("in", inp.id, t), staged, None)
            if merged:
                inp.put_staged(t, merged)
            inp.emit_time(t)
        result.epoch_failed = True
        t0 = _time.perf_counter()
        span = (
            telemetry.epoch_span(t, result.epochs)
            if telemetry is not None
            else _nullcontext()
        )
        with span:
            scope.run_epoch(t)
        epoch_hist.observe((_time.perf_counter() - t0) * 1000.0)
        blackbox.record(
            "epoch", time=t, index=result.epochs, worker=mesh.worker_id
        )
        result.epoch_failed = False
        drain_spins = 0  # an input-driven epoch proves progress
        last_time = t
        result.last_time = t
        result.epochs += 1
        if profiler is not None:
            profiler.on_epoch(scope, result.epochs)
        if freshness is not None:
            freshness.after_epoch(scope)
        _ack_sources(pollers, persisted=False, up_to_time=t)
        if prober is not None and prober.callbacks:
            prober.update(epochs=result.epochs)
        if max_epochs is not None and result.epochs >= max_epochs:
            break
    scope.current_time = max(scope.current_time, last_time)
    if result.handoff_to is not None:
        return  # live handoff: see the solo loop's exit note
    scope.finish()
    result.clean_finish = True
    if prober is not None:
        prober.update(done=True, epochs=result.epochs)


def run_pipeline_to_completion(sink_tables: list[tuple[Table, Callable]], **kwargs) -> RunResult:
    """Internal: run only the given (table, attach) sinks, not the global G."""
    scope = df.Scope()
    scope.terminate_on_error = kwargs.get("terminate_on_error", True)
    lowerer = Lowerer(scope)
    for table, attach in sink_tables:
        node = lowerer.node(table)
        attach(lowerer, node)
    df.infer_append_only(scope)
    result = RunResult()
    try:
        _event_loop(scope, lowerer, result)
    finally:
        for cleanup in lowerer.cleanups:
            try:
                cleanup()
            except Exception:
                pass
    return result
