"""Class-based row transformers with inter-row pointer references.

Parity target: ``python/pathway/internals/row_transformer.py`` (+ the
engine's ``complex_columns.rs``): ``@pw.transformer`` wraps a class of
inner ``pw.ClassArg`` tables; attributes computed for one row may follow
``Pointer`` values into any row of any inner table
(``self.transformer.other[ptr].attr``), recursively.

Engine mapping: the reference lowers each attribute into engine
``Computer``s with per-attribute dependency tracking.  Here a transformer
output table is one dataflow node that keeps its inputs' state, lazily
recomputes attributes with per-epoch memoization (each (table, row,
attribute) computed at most once per epoch, cycles detected), and emits
only the rows whose outputs changed — the same observable incremental
behavior with host-side bookkeeping kept off the device path (this
subsystem is row-wise Python by construction and never touches the MXU).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable

from pathway_tpu.engine import dataflow as df
from pathway_tpu.engine.types import KEY_MASK, Pointer, hash_values
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Lowerer, Table


# --- attribute markers ------------------------------------------------------


class _Marker:
    name: str = ""

    def __set_name__(self, owner, name):
        self.name = name


class _InputAttribute(_Marker):
    def __init__(self, **params):
        self.params = params


class _InputMethod(_Marker):
    def __init__(self, dtype=None, **params):
        self.dtype = dtype
        self.params = params


class _Computed(_Marker):
    def __init__(self, func: Callable, *, output: bool):
        self.func = func
        self.output = output


class _Method(_Marker):
    def __init__(self, func: Callable):
        self.func = func


def input_attribute(type: Any = None, **params) -> Any:
    """Declare a column taken from the input table (reference ``input_attribute``)."""
    return _InputAttribute(type=type, **params)


def input_method(type: Any = None, **params) -> Any:
    """Declare an input column holding callables (reference ``input_method``)."""
    return _InputMethod(dtype=type, **params)


def attribute(func: Callable) -> Any:
    """Computed attribute, not exported to the output table."""
    return _Computed(func, output=False)


def output_attribute(func: Callable) -> Any:
    """Computed attribute exported as an output column."""
    return _Computed(func, output=True)


def method(func: Callable) -> Any:
    """Exported method: the output column holds a callable per row."""
    return _Method(func)


# --- ClassArg ---------------------------------------------------------------


class ClassArg:
    """Base for transformer inner classes (reference ``ClassArg``).

    Subclassing collects the attribute markers; instances are row
    references created by the evaluator at compute time.
    """

    _input_attrs: dict[str, _InputAttribute]
    _input_methods: dict[str, _InputMethod]
    _computed: dict[str, _Computed]
    _methods: dict[str, _Method]
    _input_schema: type | None
    _output_schema: type | None

    def __init_subclass__(cls, /, input: type | None = None, output: type | None = None, **kwargs):
        super().__init_subclass__(**kwargs)
        cls._input_schema = input
        cls._output_schema = output
        cls._input_attrs = {}
        cls._input_methods = {}
        cls._computed = {}
        cls._methods = {}
        for name, value in list(vars(cls).items()):
            if isinstance(value, _InputAttribute):
                cls._input_attrs[name] = value
            elif isinstance(value, _InputMethod):
                cls._input_methods[name] = value
            elif isinstance(value, _Computed):
                cls._computed[name] = value
            elif isinstance(value, _Method):
                cls._methods[name] = value


# --- evaluation -------------------------------------------------------------


class _CycleError(RuntimeError):
    pass


class RowReference:
    """``self`` inside attribute functions; follows pointers lazily."""

    __slots__ = ("_ev", "_table", "_key")

    def __init__(self, ev: "_Evaluator", table: str, key: int):
        self._ev = ev
        self._table = table
        self._key = key

    @property
    def id(self) -> Pointer:
        return Pointer(self._key)

    @property
    def transformer(self) -> "_TransformerRef":
        return _TransformerRef(self._ev)

    def pointer_from(self, *args, optional: bool = False) -> Pointer | None:
        if optional and any(a is None for a in args):
            return None
        return Pointer(hash_values(list(args)))

    def __getattr__(self, name: str):
        return self._ev.value(self._table, self._key, name)


class _TableRef:
    __slots__ = ("_ev", "_table")

    def __init__(self, ev: "_Evaluator", table: str):
        self._ev = ev
        self._table = table

    def __getitem__(self, ptr) -> RowReference:
        key = ptr.value if isinstance(ptr, Pointer) else int(ptr) & KEY_MASK
        return RowReference(self._ev, self._table, key)


class _TransformerRef:
    __slots__ = ("_ev",)

    def __init__(self, ev: "_Evaluator"):
        self._ev = ev

    def __getattr__(self, table: str) -> _TableRef:
        if table not in self._ev.classes:
            raise AttributeError(f"transformer has no table {table!r}")
        return _TableRef(self._ev, table)


class _Evaluator:
    """Per-epoch lazy attribute evaluation with memoization."""

    def __init__(self, classes: dict[str, type[ClassArg]], states: dict[str, dict[int, tuple]], input_names: dict[str, list[str]]):
        self.classes = classes
        self.states = states  # table -> key -> input row tuple
        self.input_names = input_names  # table -> input column order
        self.input_index = {
            t: {n: i for i, n in enumerate(names)}
            for t, names in input_names.items()
        }
        self.memo: dict[tuple[str, int, str], Any] = {}
        self.in_progress: set[tuple[str, int, str]] = set()

    def value(self, table: str, key: int, name: str):
        cls = self.classes[table]
        if name in cls._input_attrs or name in cls._input_methods:
            row = self.states[table].get(key)
            if row is None:
                raise KeyError(
                    f"row {Pointer(key)!r} is missing from transformer table {table!r}"
                )
            return row[self.input_index[table][name]]
        if name in cls._computed:
            slot = (table, key, name)
            if slot in self.memo:
                return self.memo[slot]
            if slot in self.in_progress:
                raise _CycleError(
                    f"cyclic attribute dependency at {table}.{name} for {Pointer(key)!r}"
                )
            self.in_progress.add(slot)
            try:
                result = cls._computed[name].func(RowReference(self, table, key))
            finally:
                self.in_progress.discard(slot)
            self.memo[slot] = result
            return result
        if name in cls._methods:
            func = cls._methods[name].func
            ref = RowReference(self, table, key)
            return lambda *args, **kwargs: func(ref, *args, **kwargs)
        # plain class helpers/constants (reference: aux objects pass through)
        value = getattr(cls, name)
        if callable(value) and not isinstance(value, (staticmethod, classmethod)):
            ref = RowReference(self, table, key)
            return lambda *args, **kwargs: value(ref, *args, **kwargs)
        return value


# --- dataflow node ----------------------------------------------------------


class _MethodCell:
    """Stable per-(row, method) callable: evaluates against the node's
    CURRENT input state at call time.  Identity-stable across epochs so
    method columns don't defeat the node's change diffing (a fresh lambda
    per epoch would retract+reinsert every row on every input change)."""

    __slots__ = ("node", "table", "key", "name")

    def __init__(self, node: "_TransformerNode", table: str, key: int, name: str):
        self.node = node
        self.table = table
        self.key = key
        self.name = name

    def __call__(self, *args, **kwargs):
        ev = self.node.evaluator()
        func = self.node.classes[self.table]._methods[self.name].func
        return func(RowReference(ev, self.table, self.key), *args, **kwargs)


class _TransformerNode(df.Node):
    """Recompute-and-diff: emits changed output rows each epoch."""

    name = "row_transformer"

    def __init__(self, scope, inputs, classes, input_names, table_name, out_names):
        super().__init__(scope, inputs)
        self.classes = classes
        self.input_names = input_names
        self.table_name = table_name
        cls = classes[table_name]
        self.attr_names = [n for n in out_names if n not in cls._methods]
        self.method_names = [n for n in out_names if n in cls._methods]
        self.out_names = out_names
        self.table_order = list(classes.keys())
        self._prev: dict[int, tuple] = {}
        self._cells: dict[tuple[int, str], _MethodCell] = {}

    def evaluator(self) -> _Evaluator:
        states = {
            t: self.inputs[i].state for i, t in enumerate(self.table_order)
        }
        return _Evaluator(self.classes, states, self.input_names)

    def _cell(self, key: int, name: str) -> _MethodCell:
        slot = (key, name)
        cell = self._cells.get(slot)
        if cell is None:
            cell = self._cells[slot] = _MethodCell(self, self.table_name, key, name)
        return cell

    def step(self, time):
        changed = False
        for port in range(len(self.inputs)):
            if self.take_pending(port):
                changed = True
        if not changed:
            return
        ev = self.evaluator()
        out: dict[int, tuple] = {}
        for key in ev.states[self.table_name]:
            out[key] = tuple(
                ev.value(self.table_name, key, n) for n in self.attr_names
            ) + tuple(self._cell(key, n) for n in self.method_names)
        deltas = []
        for key, row in out.items():
            prev = self._prev.get(key)
            if prev != row:
                if prev is not None:
                    deltas.append((key, prev, -1))
                deltas.append((key, row, 1))
        for key, prev in self._prev.items():
            if key not in out:
                deltas.append((key, prev, -1))
                for name in self.method_names:
                    self._cells.pop((key, name), None)
        self._prev = out
        self.send(deltas, time)


# --- the decorator ----------------------------------------------------------


class RowTransformer:
    def __init__(self, name: str, classes: dict[str, type[ClassArg]]):
        self.name = name
        self.classes = classes

    def __call__(self, *args: Table, **kwargs: Table):
        tables: dict[str, Table] = dict(zip(self.classes, args))
        tables.update(kwargs)
        missing = set(self.classes) - set(tables)
        if missing:
            raise ValueError(f"transformer {self.name}: missing tables {sorted(missing)}")
        input_names = {
            t: list(tables[t].column_names()) for t in self.classes
        }
        for tname, cls in self.classes.items():
            declared = set(cls._input_attrs) | set(cls._input_methods)
            absent = declared - set(input_names[tname])
            if absent:
                raise ValueError(
                    f"transformer {self.name}: table {tname!r} lacks input "
                    f"columns {sorted(absent)}"
                )
        result = _TransformerResult()
        for tname, cls in self.classes.items():
            out_names = [n for n, c in cls._computed.items() if c.output]
            out_names += list(cls._methods)
            setattr(
                result,
                tname,
                self._output_table(tname, tables, input_names, out_names),
            )
        return result

    def _output_table(self, table_name, tables, input_names, out_names):
        classes = self.classes
        cls = classes[table_name]
        cols = {}
        hints = {}
        if cls._output_schema is not None:
            hints = cls._output_schema.typehints()
        for n in out_names:
            dtype = dt.wrap(hints[n]) if n in hints else dt.ANY
            cols[n] = schema_mod.ColumnSchema(name=n, dtype=dtype)
        out_schema = schema_mod.schema_from_columns(cols, name=f"{self.name}_{table_name}")

        def build(lowerer: Lowerer) -> df.Node:
            nodes = [
                lowerer.node(tables[t]).require_state() for t in classes
            ]
            return _TransformerNode(
                lowerer.scope, nodes, classes, input_names, table_name, out_names
            )

        return Table(out_schema, build, universe=tables[table_name]._universe)


class _TransformerResult:
    pass


def transformer(cls: type) -> RowTransformer:
    """``@pw.transformer`` — collect inner ClassArg tables (reference
    ``decorators.py:58`` / ``row_transformer.py:38``)."""
    classes = {
        name: value
        for name, value in vars(cls).items()
        if isinstance(value, type) and issubclass(value, ClassArg)
    }
    if not classes:
        raise TypeError(
            f"@transformer class {cls.__name__} declares no ClassArg tables"
        )
    return RowTransformer(cls.__name__, classes)
