"""UDF system: ``@pw.udf`` with sync/async executors, caching, retries.

Parity target: ``/root/reference/python/pathway/internals/udfs/__init__.py``
(UDF/UDFFunction, :65,:211), ``executors.py`` (auto/sync/async), ``caches.py``
(CacheStrategy/DiskCache/InMemoryCache), ``retries.py``.

TPU note: sync UDFs are evaluated per-row host-side like the reference's
GIL-batched path; array-valued deterministic UDFs over jax are the escape
hatch the xpack embedders use (they batch row deltas into device arrays —
see pathway_tpu/utils/batching.py).
"""

from __future__ import annotations

import asyncio
import functools
import inspect
import typing
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    ColumnExpression,
)
from pathway_tpu.internals.udfs.caches import (
    CacheStrategy,
    DefaultCache,
    DiskCache,
    InMemoryCache,
)
from pathway_tpu.internals.udfs.executors import (
    Executor,
    async_executor,
    async_options,
    auto_executor,
    fully_async_executor,
    sync_executor,
)
from pathway_tpu.internals.udfs.retries import (
    AsyncRetryStrategy,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    NoRetryStrategy,
)

__all__ = [
    "udf",
    "UDF",
    "auto_executor",
    "async_executor",
    "async_options",
    "sync_executor",
    "fully_async_executor",
    "CacheStrategy",
    "DefaultCache",
    "DiskCache",
    "InMemoryCache",
    "AsyncRetryStrategy",
    "ExponentialBackoffRetryStrategy",
    "FixedDelayRetryStrategy",
    "NoRetryStrategy",
    "coerce_async",
    "with_cache_strategy",
    "with_capacity",
    "with_retry_strategy",
    "with_timeout",
]


class UDF:
    """Base class for user-defined functions (subclass and define __wrapped__)."""

    def __init__(
        self,
        *,
        return_type: Any = None,
        deterministic: bool = False,
        propagate_none: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
        max_batch_size: int | None = None,
    ):
        self.return_type = return_type
        self.deterministic = deterministic
        self.propagate_none = propagate_none
        self.executor = executor or auto_executor()
        self.cache_strategy = cache_strategy
        self.max_batch_size = max_batch_size
        self.__wrapped__: Callable | None = getattr(self, "__wrapped__", None)

    def _resolve_return_type(self, fun: Callable) -> Any:
        if self.return_type is not None:
            return self.return_type
        try:
            hints = typing.get_type_hints(fun)
            return hints.get("return")
        except Exception:
            return None

    def _wrapped_fun(self) -> Callable:
        fun = self.__wrapped__
        if fun is None:
            raise TypeError("UDF subclass must define __wrapped__")
        if self.cache_strategy is not None:
            fun = self.cache_strategy.wrap(fun)
        return fun

    def as_async_callable(self) -> Callable:
        """The UDF's function as a directly-awaitable callable with its
        configured cache strategy, retry strategy, capacity, and timeout
        applied — for host-side callers (RAG handlers) that invoke the
        model outside a dataflow expression."""
        fun = self._wrapped_fun()
        fun = self.executor.wrap_async(fun)
        return coerce_async(fun)

    def __call__(self, *args, **kwargs) -> ColumnExpression:
        fun = self._wrapped_fun()
        ret = self._resolve_return_type(self.__wrapped__)
        if asyncio.iscoroutinefunction(self.__wrapped__) or getattr(
            self.executor, "is_async", False
        ):
            fun = self.executor.wrap_async(fun)
            return AsyncApplyExpression(
                fun,
                ret,
                *args,
                _propagate_none=self.propagate_none,
                _deterministic=self.deterministic,
                **kwargs,
            )
        fun = self.executor.wrap_sync(fun)
        return ApplyExpression(
            fun,
            ret,
            *args,
            _propagate_none=self.propagate_none,
            _deterministic=self.deterministic,
            _max_batch_size=self.max_batch_size,
            **kwargs,
        )


class _FunctionUDF(UDF):
    def __init__(self, fun: Callable, **kwargs):
        super().__init__(**kwargs)
        self.__wrapped__ = fun
        functools.update_wrapper(self, fun)


def udf(
    fun: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    deterministic: bool = False,
    propagate_none: bool = False,
    executor: Executor | None = None,
    cache_strategy: CacheStrategy | None = None,
    max_batch_size: int | None = None,
):
    r"""``@pw.udf`` — turn a Python function into a column-expression builder.

    Example:

    >>> import pathway_tpu as pw
    >>> @pw.udf
    ... def shout(s: str) -> str:
    ...     return s.upper()
    >>> t = pw.debug.table_from_markdown('w\nhi\nyo')
    >>> pw.debug.compute_and_print(t.select(loud=shout(pw.this.w)), include_id=False)
    loud
    HI
    YO
    """

    def wrapper(f: Callable) -> _FunctionUDF:
        return _FunctionUDF(
            f,
            return_type=return_type,
            deterministic=deterministic,
            propagate_none=propagate_none,
            executor=executor,
            cache_strategy=cache_strategy,
            max_batch_size=max_batch_size,
        )

    if fun is not None:
        return wrapper(fun)
    return wrapper


# helpers mirroring pathway.udfs module-level functions
def coerce_async(fun: Callable) -> Callable:
    if asyncio.iscoroutinefunction(fun):
        return fun

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return fun(*args, **kwargs)

    return wrapper


def with_cache_strategy(fun: Callable, cache_strategy: CacheStrategy) -> Callable:
    return cache_strategy.wrap(fun)


def with_capacity(fun: Callable, capacity: int) -> Callable:
    fun = coerce_async(fun)
    semaphore = asyncio.Semaphore(capacity)

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        async with semaphore:
            return await fun(*args, **kwargs)

    return wrapper


def with_retry_strategy(fun: Callable, retry_strategy: AsyncRetryStrategy) -> Callable:
    fun = coerce_async(fun)

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return await retry_strategy.invoke(fun, *args, **kwargs)

    return wrapper


def with_timeout(fun: Callable, timeout: float) -> Callable:
    fun = coerce_async(fun)

    @functools.wraps(fun)
    async def wrapper(*args, **kwargs):
        return await asyncio.wait_for(fun(*args, **kwargs), timeout=timeout)

    return wrapper
