"""Async retry strategies (parity: internals/udfs/retries.py, 116 LoC)."""

from __future__ import annotations

import asyncio
import random
from typing import Any, Callable, Iterator


class AsyncRetryStrategy:
    async def invoke(self, fun: Callable, /, *args, **kwargs) -> Any:
        raise NotImplementedError


class NoRetryStrategy(AsyncRetryStrategy):
    async def invoke(self, fun: Callable, /, *args, **kwargs) -> Any:
        return await fun(*args, **kwargs)


class ExponentialBackoffRetryStrategy(AsyncRetryStrategy):
    def __init__(
        self,
        max_retries: int = 3,
        initial_delay: int = 1_000,
        backoff_factor: float = 2,
        jitter_ms: int = 300,
    ):
        self.max_retries = max_retries
        self.initial_delay = initial_delay / 1_000
        self.backoff_factor = backoff_factor
        self.jitter = jitter_ms / 1_000

    def delays(self) -> "Iterator[float]":
        """The backoff schedule in seconds, one entry per retry (jittered).

        Shared by the async ``invoke`` below and by synchronous retriers
        (the comm mesh's link-reconnect loop, ``engine/comm.py``) so the
        whole codebase has exactly one backoff policy implementation.
        """
        delay = self.initial_delay
        for _ in range(self.max_retries):
            yield delay + random.random() * self.jitter
            delay *= self.backoff_factor

    async def invoke(self, fun: Callable, /, *args, **kwargs) -> Any:
        schedule = self.delays()
        for attempt in range(self.max_retries + 1):
            try:
                return await fun(*args, **kwargs)
            except asyncio.CancelledError:
                raise
            except Exception:
                if attempt == self.max_retries:
                    raise
                await asyncio.sleep(next(schedule))
        raise RuntimeError("unreachable")


class FixedDelayRetryStrategy(ExponentialBackoffRetryStrategy):
    def __init__(self, max_retries: int = 3, delay_ms: int = 1_000):
        super().__init__(
            max_retries=max_retries,
            initial_delay=delay_ms,
            backoff_factor=1,
            jitter_ms=0,
        )
