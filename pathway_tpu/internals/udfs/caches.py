"""UDF result caches (parity: internals/udfs/caches.py:23-141).

``DiskCache`` persists through the persistence layer's cached-object storage
(the reference routes it through engine persistence,
``src/persistence/cached_object_storage.rs``); here it writes one pickle per
key under the persistence root or a local cache dir.
"""

from __future__ import annotations

import asyncio
import functools
import hashlib
import os
import pickle
from typing import Any, Callable


class CacheStrategy:
    def wrap(self, fun: Callable) -> Callable:
        raise NotImplementedError

    @staticmethod
    def _cache_key(fun: Callable, args, kwargs) -> str:
        payload = pickle.dumps((getattr(fun, "__name__", "fn"), args, tuple(sorted(kwargs.items()))))
        return hashlib.blake2b(payload, digest_size=16).hexdigest()


class InMemoryCache(CacheStrategy):
    def __init__(self):
        self._store: dict[str, Any] = {}

    def wrap(self, fun: Callable) -> Callable:
        if asyncio.iscoroutinefunction(fun):

            @functools.wraps(fun)
            async def async_wrapper(*args, **kwargs):
                key = self._cache_key(fun, args, kwargs)
                if key not in self._store:
                    self._store[key] = await fun(*args, **kwargs)
                return self._store[key]

            return async_wrapper

        @functools.wraps(fun)
        def wrapper(*args, **kwargs):
            key = self._cache_key(fun, args, kwargs)
            if key not in self._store:
                self._store[key] = fun(*args, **kwargs)
            return self._store[key]

        return wrapper


class DiskCache(CacheStrategy):
    def __init__(self, name: str | None = None, size_limit: int | None = None):
        self.name = name
        self.size_limit = size_limit

    @property
    def _dir(self) -> str:
        # resolved per call: the current run's persistence root wins
        # (context-local, so concurrent runs each see their own), then the
        # env override, then a local default
        from pathway_tpu.engine import persistence as pz
        from pathway_tpu.internals.config import env_str

        root = (
            pz.active_root()
            or env_str("PATHWAY_PERSISTENT_STORAGE")
            or ".pathway_tpu_cache"
        )
        return os.path.join(root, "udf_cache", self.name or "default")

    def _path(self, key: str) -> str:
        return os.path.join(self._dir, key + ".pkl")

    def _get(self, key: str):
        path = self._path(key)
        if os.path.exists(path):
            with open(path, "rb") as f:
                return True, pickle.load(f)
        return False, None

    def _put(self, key: str, value: Any) -> None:
        os.makedirs(self._dir, exist_ok=True)
        with open(self._path(key), "wb") as f:
            pickle.dump(value, f)

    def wrap(self, fun: Callable) -> Callable:
        if asyncio.iscoroutinefunction(fun):

            @functools.wraps(fun)
            async def async_wrapper(*args, **kwargs):
                key = self._cache_key(fun, args, kwargs)
                hit, value = self._get(key)
                if hit:
                    return value
                value = await fun(*args, **kwargs)
                self._put(key, value)
                return value

            return async_wrapper

        @functools.wraps(fun)
        def wrapper(*args, **kwargs):
            key = self._cache_key(fun, args, kwargs)
            hit, value = self._get(key)
            if hit:
                return value
            value = fun(*args, **kwargs)
            self._put(key, value)
            return value

        return wrapper


DefaultCache = DiskCache
