"""UDF executors: auto / sync / async / fully_async.

Parity target: ``/root/reference/python/pathway/internals/udfs/executors.py``
(:36-154).  Async semantics follow dataflow.rs:1899-1937: all rows of a batch
are in flight concurrently; the epoch acts as a barrier (results re-enter at
the same timestamp).  ``fully_async_executor`` is the AsyncTransformer-style
non-blocking variant.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable

from pathway_tpu.internals.udfs.retries import AsyncRetryStrategy


class Executor:
    is_async = False

    def wrap_sync(self, fun: Callable) -> Callable:
        return fun

    def wrap_async(self, fun: Callable) -> Callable:
        return fun


class AutoExecutor(Executor):
    """Chooses sync for plain functions, async for coroutine functions."""


def auto_executor() -> Executor:
    return AutoExecutor()


class SyncExecutor(Executor):
    is_async = False


def sync_executor() -> Executor:
    return SyncExecutor()


class AsyncExecutor(Executor):
    is_async = True

    def __init__(
        self,
        *,
        capacity: int | None = None,
        timeout: float | None = None,
        retry_strategy: AsyncRetryStrategy | None = None,
    ):
        self.capacity = capacity
        self.timeout = timeout
        self.retry_strategy = retry_strategy

    def wrap_async(self, fun: Callable) -> Callable:
        from pathway_tpu.internals.udfs import (
            coerce_async,
            with_capacity,
            with_retry_strategy,
            with_timeout,
        )

        fun = coerce_async(fun)
        if self.retry_strategy is not None:
            fun = with_retry_strategy(fun, self.retry_strategy)
        if self.timeout is not None:
            fun = with_timeout(fun, self.timeout)
        if self.capacity is not None:
            fun = with_capacity(fun, self.capacity)
        return fun


def async_executor(
    *,
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
) -> Executor:
    return AsyncExecutor(capacity=capacity, timeout=timeout, retry_strategy=retry_strategy)


class FullyAsyncExecutor(AsyncExecutor):
    """Results arrive at later epochs instead of blocking the batch."""

    def __init__(self, *, autocommit_duration_ms: int | None = 100, **kwargs):
        super().__init__(**kwargs)
        self.autocommit_duration_ms = autocommit_duration_ms


def fully_async_executor(
    *,
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    autocommit_duration_ms: int | None = 100,
) -> Executor:
    return FullyAsyncExecutor(
        capacity=capacity,
        timeout=timeout,
        retry_strategy=retry_strategy,
        autocommit_duration_ms=autocommit_duration_ms,
    )


def async_options(
    capacity: int | None = None,
    timeout: float | None = None,
    retry_strategy: AsyncRetryStrategy | None = None,
    cache_strategy=None,
):
    """Decorator applying async options to a plain function, returning an
    AWAITABLE callable (parity: udfs/executors.py:286 — the reference
    composes the with_* wrappers, not a UDF; use ``@pw.udf`` with
    ``executor=async_executor(...)`` for the column-expression form)."""

    def decorator(fun):
        from pathway_tpu.internals.udfs import (
            coerce_async,
            with_cache_strategy,
            with_capacity,
            with_retry_strategy,
            with_timeout,
        )

        wrapped = coerce_async(fun)
        if timeout is not None:
            wrapped = with_timeout(wrapped, timeout)
        if retry_strategy is not None:
            wrapped = with_retry_strategy(wrapped, retry_strategy)
        if capacity is not None:
            wrapped = with_capacity(wrapped, capacity)
        if cache_strategy is not None:
            wrapped = with_cache_strategy(wrapped, cache_strategy)
        return wrapped

    return decorator
