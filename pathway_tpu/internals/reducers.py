"""Incremental reducers.

Parity target: ``/root/reference/src/engine/reduce.rs:22-38`` (engine side) and
``/root/reference/python/pathway/reducers.py`` (user API): count, sum (int,
float, array), min/max/argmin/argmax, unique, any, sorted_tuple, tuple,
ndarray, avg, earliest/latest, stateful_single/stateful_many, plus
``BaseCustomAccumulator`` custom reducers.

Engine contract (mirrors the semigroup-vs-full split of reduce.rs:40-61):
every reducer owns a per-group state object supporting ``add(args, diff,
time, key)`` and ``extract()``.  Invertible reducers (count/sum/avg) update
in O(1); non-invertible ones keep the group's value multiset and recompute
on change — the same strategy differential dataflow's ``reduce`` uses, minus
arrangement sharing.
"""

from __future__ import annotations

import datetime as _datetime
import itertools
from collections import Counter
from collections.abc import Sequence
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.types import ERROR, Pointer
from pathway_tpu.internals import dtype as dt


class ReducerState:
    def add(self, args: tuple, diff: int, time: int, key) -> None:
        raise NotImplementedError

    def extract(self) -> Any:
        raise NotImplementedError

    def is_empty(self) -> bool:
        raise NotImplementedError

    # operator-snapshot hooks (persistence/operator_snapshot.rs analog):
    # dump() returns plain picklable data; load() restores it into a state
    # freshly created by Reducer.make_state(), which re-binds any callables
    def dump(self) -> Any:
        raise NotImplementedError(f"{type(self).__name__} is not persistable")

    def load(self, data: Any) -> None:
        raise NotImplementedError(f"{type(self).__name__} is not persistable")


class Reducer:
    name: str = "reducer"

    def result_dtype(self, arg_dtypes: list[dt.DType]) -> dt.DType:
        return dt.ANY

    def make_state(self) -> ReducerState:
        raise NotImplementedError

    def make_append_state(self) -> ReducerState:
        """State variant for groups fed by an append-only input stream
        (``Node.append_only``): never sees retractions, so non-invertible
        reducers may keep O(1) running entries instead of value multisets.
        Default: same as ``make_state`` (already O(1) or order-dependent)."""
        return self.make_state()

    def __call__(self, *args, **kwargs):
        from pathway_tpu.internals.expression import ReducerExpression

        return ReducerExpression(self, *args, **kwargs)

    def __repr__(self):
        return f"pw.reducers.{self.name}"


class _CountState(ReducerState):
    __slots__ = ("n",)

    def __init__(self):
        self.n = 0

    def add(self, args, diff, time, key):
        self.n += diff

    def add_bulk(self, n_contrib: int) -> None:
        """Columnar path: fold a whole batch's diff total in one call."""
        self.n += n_contrib

    def extract(self):
        return self.n

    def is_empty(self):
        return self.n == 0

    def dump(self):
        return self.n

    def load(self, data):
        self.n = data


class CountReducer(Reducer):
    name = "count"

    def result_dtype(self, arg_dtypes):
        return dt.INT

    def make_state(self):
        return _CountState()


class _SumState(ReducerState):
    __slots__ = ("total", "n", "is_array")

    def __init__(self):
        self.total = None
        self.n = 0

    def add(self, args, diff, time, key):
        (v,) = args
        if v is None:
            return
        contrib = v * diff if diff != 1 else v
        if self.total is None:
            self.total = contrib if diff == 1 else contrib
        else:
            self.total = self.total + contrib
        self.n += diff

    def add_bulk(self, total_contrib, n_contrib: int) -> None:
        """Columnar path: Σ value·diff and Σ diff for a batch (no Nones —
        the vector path only runs on typed columns)."""
        if self.total is None:
            self.total = total_contrib
        else:
            self.total = self.total + total_contrib
        self.n += n_contrib

    def extract(self):
        if self.total is None:
            return 0
        if isinstance(self.total, float):
            return self.total
        return self.total

    def is_empty(self):
        return self.n == 0

    def dump(self):
        return (self.total, self.n)

    def load(self, data):
        self.total, self.n = data


class SumReducer(Reducer):
    name = "sum"

    def result_dtype(self, arg_dtypes):
        t = arg_dtypes[0].strip_optional() if arg_dtypes else dt.ANY
        if t in (dt.INT, dt.FLOAT, dt.DURATION) or isinstance(t, dt._Array):
            return t
        return dt.ANY

    def make_state(self):
        return _SumState()


class _AvgState(_SumState):
    def extract(self):
        if self.n == 0:
            return None
        return self.total / self.n


class AvgReducer(Reducer):
    name = "avg"

    def result_dtype(self, arg_dtypes):
        return dt.FLOAT

    def make_state(self):
        return _AvgState()


def _sort_key(v):
    # deterministic total order: numbers compare numerically across
    # bool/int/float; other types are grouped and ordered within the group
    if isinstance(v, (bool, int, float)):
        return (0, float(v))
    if isinstance(v, str):
        return (1, v)
    if isinstance(v, bytes):
        return (2, v)
    if isinstance(v, _builtin_tuple):
        return (3, _builtin_tuple(_sort_key(x) for x in v))
    if isinstance(v, Pointer):
        return (4, v.value)
    if isinstance(v, _datetime.datetime):
        if v.tzinfo is not None:
            return (6, 1, v.astimezone(_datetime.timezone.utc).isoformat())
        return (6, 0, v.isoformat())
    if isinstance(v, _datetime.timedelta):
        return (7, v.total_seconds())
    return (5, str(type(v).__name__), repr(v))


class _MultisetState(ReducerState):
    """Counter-of-rows state for non-invertible reducers.

    ``keyed=False`` collapses entries by VALUE: reducers that never look at
    the row key (min/max/unique/sorted_tuple) then hold one counter per
    distinct value instead of one per contributing row — the semigroup-style
    compaction the reference applies to these reducers (reduce.rs:40-61),
    bounding per-group memory on high-churn groups."""

    __slots__ = ("rows", "finish", "keyed")

    def __init__(self, finish: Callable[[Counter], Any], keyed: bool = True):
        self.rows = Counter()
        self.finish = finish
        self.keyed = keyed

    def add(self, args, diff, time, key):
        entry = (args, key if self.keyed else None)
        self.rows[entry] += diff
        if self.rows[entry] == 0:
            del self.rows[entry]

    def add_pairs(self, values, counts):
        """Columnar bulk update: per distinct value, a summed diff.
        Only valid for ``keyed=False`` states (min/max/...)."""
        rows = self.rows
        for v, c in zip(values, counts):
            entry = ((v,), None)
            rows[entry] += c
            if rows[entry] == 0:
                del rows[entry]

    def extract(self):
        return self.finish(self.rows)

    def is_empty(self):
        return not self.rows

    def dump(self):
        return self.rows

    def load(self, data):
        _reject_running_dump(data)
        if self.keyed:
            self.rows = Counter(data)
            return
        # snapshots written before value-collapsing keep (args, key)
        # entries — normalize so later retractions (args, None) cancel them
        self.rows = Counter()
        for (args, _key), cnt in Counter(data).items():
            self.rows[(args, None)] += cnt
        for entry in [e for e, c in self.rows.items() if c == 0]:
            del self.rows[entry]


def _reject_running_dump(data) -> None:
    """Multiset/time states must refuse a running-state dump (the other
    direction of the _RunningState.load guard): a snapshot written while
    the source was append-only cannot resume after the declaration was
    dropped — Counter(data) would silently build garbage state."""
    if (
        isinstance(data, _builtin_tuple)
        and len(data) == 3
        and data[0] in ("ro1", "ru1")
    ):
        raise ValueError(
            "operator snapshot holds an append-only reducer state but the "
            "source is no longer append-only; resume with the original "
            "schema properties or clear the persistence dir"
        )


def _append_only_violation():
    from pathway_tpu.engine.dataflow import EngineError

    raise EngineError(
        "retraction reached an append-only reduction state: the input "
        "stream was inferred append-only (declared via "
        "column_definition(append_only=True) or a retraction-free source) "
        "but produced a deletion"
    )


class _RunningState(ReducerState):
    """O(1) accumulator for groups fed by an append-only stream.

    Non-invertible reducers (min/max/argmin/argmax/any/earliest/latest)
    need their value multiset only to survive retractions; when the lowered
    input can never retract (``Node.append_only``) a single running entry
    suffices.  This is the operator-variant choice the reference drives off
    column append-onlyness (``internals/column_properties.py``; engine
    switches ``src/engine/dataflow.rs:1741``).

    ``enter(args, time, key)`` builds a comparable entry; ``better`` says
    whether a new entry replaces the running one (strict — ties keep the
    first arrival, matching multiset iteration order); ``result`` maps the
    running entry to the reducer output.
    """

    __slots__ = ("entry", "n", "enter", "better", "result")

    def __init__(self, enter: Callable, better: Callable, result: Callable):
        self.entry = None
        self.n = 0
        self.enter = enter
        self.better = better
        self.result = result

    def add(self, args, diff, time, key):
        if diff < 0:
            _append_only_violation()
        self.n += diff
        e = self.enter(args, time, key)
        if self.entry is None or self.better(e, self.entry):
            self.entry = e

    def add_pairs(self, values, counts):
        """Columnar bulk update (GroupByNode "mm" path): per distinct
        value, a summed diff — only keyless reducers (min/max) get here."""
        enter, better = self.enter, self.better
        for v, c in zip(values, counts):
            if c < 0:
                _append_only_violation()
            self.n += c
            e = enter((v,), 0, None)
            if self.entry is None or better(e, self.entry):
                self.entry = e

    def extract(self):
        return self.result(self.entry)

    def is_empty(self):
        return self.n <= 0

    def dump(self):
        return ("ro1", self.entry, self.n)

    def load(self, data):
        if not (isinstance(data, _builtin_tuple) and len(data) == 3 and data[0] == "ro1"):
            raise ValueError(
                "operator snapshot holds a multiset reducer state but the "
                "source is now append-only (or vice versa); resume with the "
                "original schema properties or clear the persistence dir"
            )
        _, self.entry, self.n = data


def _running_min_factory(latest: bool):
    def enter(args, time, key):
        return (_sort_key(args[0]), args[0])

    def better(e, cur):
        return e[0] > cur[0] if latest else e[0] < cur[0]

    return lambda: _RunningState(enter, better, lambda e: e[1])


_running_states: dict[str, Callable[[], _RunningState]] = {
    "min": _running_min_factory(latest=False),
    "max": _running_min_factory(latest=True),
    # argmin: min by (value sort key, row key) — the tie rule of
    # _finish_argmin; argmax: max by value, tie broken by MIN row key
    "argmin": lambda: _RunningState(
        lambda a, t, k: (_sort_key(a[0]), k),
        lambda e, c: e < c,
        lambda e: e[1] if isinstance(e[1], Pointer) else Pointer(e[1]),
    ),
    "argmax": lambda: _RunningState(
        lambda a, t, k: (_sort_key(a[0]), k),
        lambda e, c: e[0] > c[0] or (e[0] == c[0] and e[1] < c[1]),
        lambda e: e[1] if isinstance(e[1], Pointer) else Pointer(e[1]),
    ),
    # any: the row with the smallest key (the _finish_any pick)
    "any": lambda: _RunningState(
        lambda a, t, k: (k, a[0]),
        lambda e, c: e[0] < c[0],
        lambda e: e[1],
    ),
}


class _RunningUniqueState(ReducerState):
    """Append-only ``unique``: remembers at most two distinct non-None
    values — two suffice to report ERROR, exactly as _finish_unique."""

    __slots__ = ("vals", "n")

    def __init__(self):
        self.vals: list = []
        self.n = 0

    def add(self, args, diff, time, key):
        if diff < 0:
            _append_only_violation()
        self.n += diff
        v = args[0]
        if v is not None and v not in self.vals and len(self.vals) < 2:
            self.vals.append(v)

    def add_pairs(self, values, counts):
        for v, c in zip(values, counts):
            self.add((v,), c, 0, None)

    def extract(self):
        if len(self.vals) > 1:
            return ERROR
        return self.vals[0] if self.vals else None

    def is_empty(self):
        return self.n <= 0

    def dump(self):
        return ("ru1", self.vals, self.n)

    def load(self, data):
        if not (isinstance(data, _builtin_tuple) and len(data) == 3 and data[0] == "ru1"):
            raise ValueError(
                "operator snapshot reducer-state format mismatch (see "
                "_RunningState.load)"
            )
        _, self.vals, self.n = data


def _multiset_reducer(
    name_: str, finish: Callable[[Counter], Any], rdtype=None, keyed: bool = True
):
    class _R(Reducer):
        name = name_

        def result_dtype(self, arg_dtypes):
            if rdtype is not None:
                return rdtype if isinstance(rdtype, dt.DType) else rdtype(arg_dtypes)
            return arg_dtypes[0] if arg_dtypes else dt.ANY

        def make_state(self):
            return _MultisetState(finish, keyed=keyed)

        def make_append_state(self):
            if name_ == "unique":
                return _RunningUniqueState()
            factory = _running_states.get(name_)
            return factory() if factory is not None else self.make_state()

    _R.__name__ = f"{name_.title()}Reducer"
    return _R()


# `min`/`max`/`sum`/`any`/`tuple` are shadowed below by the public reducer
# instances (mirroring pw.reducers naming); keep the builtins reachable.
_builtin_min = min
_builtin_max = max
_builtin_sum = sum
_builtin_any = any
_builtin_tuple = tuple


def _finish_min(rows: Counter):
    return _builtin_min((a[0] for (a, k) in rows), key=_sort_key)


def _finish_max(rows: Counter):
    return _builtin_max((a[0] for (a, k) in rows), key=_sort_key)


def _finish_argmin(rows: Counter):
    best = _builtin_min(rows, key=lambda e: (_sort_key(e[0][0]), e[1]))
    return Pointer(best[1]) if not isinstance(best[1], Pointer) else best[1]


def _finish_argmax(rows: Counter):
    mx = _builtin_max(_sort_key(e[0][0]) for e in rows)
    best = _builtin_min((e for e in rows if _sort_key(e[0][0]) == mx), key=lambda e: e[1])
    return Pointer(best[1]) if not isinstance(best[1], Pointer) else best[1]


def _finish_unique(rows: Counter):
    vals = {a[0] for (a, k) in rows if a[0] is not None}
    if len(vals) > 1:
        return ERROR
    return next(iter(vals), None)


def _finish_any(rows: Counter):
    return _builtin_min(((a, k) for (a, k) in rows), key=lambda e: e[1])[0][0]


def _finish_sorted_tuple_factory(skip_nones: bool):
    def finish(rows: Counter):
        out = []
        for (a, k), cnt in rows.items():
            v = a[0]
            if skip_nones and v is None:
                continue
            out.extend([v] * cnt)
        out.sort(key=_sort_key)
        return _builtin_tuple(out)

    return finish


def _finish_tuple_factory(skip_nones: bool):
    def finish(rows: Counter):
        entries = []
        for (a, k), cnt in rows.items():
            v = a[0]
            if skip_nones and v is None:
                continue
            # order by the sort column when 2 args are given (tuple(x, sort_by=...)),
            # else by row key — matching reference tuple reducer ordering
            sort_v = a[1] if len(a) > 1 else k
            entries.extend([(sort_v, k, v)] * cnt)
        entries.sort(key=lambda e: (_sort_key(e[0]), e[1]))
        return _builtin_tuple(v for (_, _, v) in entries)

    return finish


def _finish_ndarray_factory(skip_nones: bool):
    def finish(rows: Counter):
        tup = _finish_tuple_factory(skip_nones)(rows)
        return np.array(tup)

    return finish


class _TimeBasedState(ReducerState):
    """earliest/latest — value at min/max processing time."""

    __slots__ = ("rows", "latest")

    def __init__(self, latest: bool):
        self.rows = Counter()
        self.latest = latest

    def add(self, args, diff, time, key):
        entry = (time, key, args)
        self.rows[entry] += diff
        if self.rows[entry] == 0:
            del self.rows[entry]

    def extract(self):
        pick = _builtin_max if self.latest else _builtin_min
        best = pick(self.rows, key=lambda e: (e[0], e[1]))
        return best[2][0]

    def is_empty(self):
        return not self.rows

    def dump(self):
        return self.rows

    def load(self, data):
        _reject_running_dump(data)
        self.rows = Counter(data)


def _time_running_state(latest: bool) -> _RunningState:
    return _RunningState(
        lambda a, t, k: ((t, k), a[0]),
        (lambda e, c: e[0] > c[0]) if latest else (lambda e, c: e[0] < c[0]),
        lambda e: e[1],
    )


class EarliestReducer(Reducer):
    name = "earliest"

    def result_dtype(self, arg_dtypes):
        return arg_dtypes[0] if arg_dtypes else dt.ANY

    def make_state(self):
        return _TimeBasedState(latest=False)

    def make_append_state(self):
        return _time_running_state(latest=False)


class LatestReducer(Reducer):
    name = "latest"

    def result_dtype(self, arg_dtypes):
        return arg_dtypes[0] if arg_dtypes else dt.ANY

    def make_state(self):
        return _TimeBasedState(latest=True)

    def make_append_state(self):
        return _time_running_state(latest=True)


class _StatefulState(ReducerState):
    """Recompute a Python combiner over the group multiset (reduce.rs Stateful)."""

    __slots__ = ("rows", "combine", "many")

    def __init__(self, combine: Callable, many: bool):
        self.rows = Counter()
        self.combine = combine
        self.many = many

    def add(self, args, diff, time, key):
        entry = (args, key)
        self.rows[entry] += diff
        if self.rows[entry] == 0:
            del self.rows[entry]

    def extract(self):
        values = []
        for (a, k), cnt in sorted(self.rows.items(), key=lambda e: e[0][1]):
            values.extend([a] * cnt)
        if self.many:
            return self.combine(None, [(1, v) for v in values])
        state = None
        for v in values:
            state = self.combine(state, *v)
        return state

    def is_empty(self):
        return not self.rows

    def dump(self):
        return self.rows

    def load(self, data):
        self.rows = Counter(data)


class StatefulReducer(Reducer):
    def __init__(self, combine: Callable, many: bool, name: str = "stateful"):
        self._combine = combine
        self._many = many
        self.name = name

    def result_dtype(self, arg_dtypes):
        import typing

        try:
            hints = typing.get_type_hints(self._combine)
            if "return" in hints:
                return dt.wrap(hints["return"])
        except Exception:
            pass
        return dt.ANY

    def make_state(self):
        return _StatefulState(self._combine, self._many)


def stateful_single(combine_fn: Callable) -> StatefulReducer:
    r"""pw.reducers.stateful_single — state = combine(state, *row_values).

    Example:

    >>> import pathway_tpu as pw
    >>> concat = pw.reducers.stateful_single(
    ...     lambda state, v: (state or '') + v
    ... )
    >>> t = pw.debug.table_from_markdown('k | v\na | x\na | y')
    >>> r = t.groupby(pw.this.k).reduce(pw.this.k, s=concat(pw.this.v))
    >>> pw.debug.compute_and_print(r, include_id=False)
    k | s
    a | xy
    """
    return StatefulReducer(combine_fn, many=False, name=getattr(combine_fn, "__name__", "stateful"))


def stateful_many(combine_fn: Callable) -> StatefulReducer:
    """pw.reducers.stateful_many — combine(state, [(diff, row), ...])."""
    return StatefulReducer(combine_fn, many=True, name=getattr(combine_fn, "__name__", "stateful"))


class BaseCustomAccumulator:
    """User-defined accumulator (pw.BaseCustomAccumulator).

    Subclasses implement ``from_row``, ``update``, optionally ``retract`` and
    ``neutral``, and ``compute_result``.
    """

    @classmethod
    def from_row(cls, row):
        raise NotImplementedError

    def update(self, other):
        raise NotImplementedError

    def retract(self, other):
        raise NotImplementedError

    def compute_result(self):
        raise NotImplementedError


class _CustomAccState(ReducerState):
    __slots__ = ("rows", "acc_cls", "order", "_seq")

    def __init__(self, acc_cls):
        self.rows = Counter()
        self.acc_cls = acc_cls
        # arrival order (time, seq) per entry: order-sensitive accumulators
        # (HMM) must replay in processing order, matching how the reference
        # engine applies stateful updates per timestamp — keys are hashes
        # and carry no ordering
        self.order: dict = {}
        self._seq = itertools.count()

    def add(self, args, diff, time, key):
        entry = (args, key)
        if entry not in self.order:
            self.order[entry] = (time, next(self._seq))
        self.rows[entry] += diff
        if self.rows[entry] == 0:
            del self.rows[entry]
            del self.order[entry]

    def extract(self):
        acc = None
        for (a, _k), cnt in sorted(self.rows.items(), key=lambda e: self.order[e[0]]):
            for _ in range(cnt):
                nxt = self.acc_cls.from_row(list(a))
                if acc is None:
                    acc = nxt
                else:
                    acc.update(nxt)
        return acc.compute_result() if acc is not None else None

    def is_empty(self):
        return not self.rows

    def dump(self):
        return (self.rows, self.order, max((s for (_t, s) in self.order.values()), default=-1) + 1)

    def load(self, data):
        rows, order, seq_next = data
        self.rows = Counter(rows)
        self.order = dict(order)
        self._seq = itertools.count(seq_next)


def udf_reducer(accumulator: type[BaseCustomAccumulator]):
    r"""Custom reducer from a ``BaseCustomAccumulator`` subclass (supports retractions).

    Example:

    >>> import pathway_tpu as pw
    >>> class Sum(pw.BaseCustomAccumulator):
    ...     def __init__(self, v):
    ...         self.s = v
    ...     @classmethod
    ...     def from_row(cls, row):
    ...         return cls(row[0])
    ...     def update(self, other):
    ...         self.s += other.s
    ...     def retract(self, other):
    ...         self.s -= other.s
    ...     def compute_result(self):
    ...         return self.s
    >>> ssum = pw.reducers.udf_reducer(Sum)
    >>> t = pw.debug.table_from_markdown('k | v\na | 2\na | 3')
    >>> pw.debug.compute_and_print(t.groupby(pw.this.k).reduce(pw.this.k, s=ssum(pw.this.v)), include_id=False)
    k | s
    a | 5
    """
    class _R(Reducer):
        name = getattr(accumulator, "__name__", "custom")

        def result_dtype(self, arg_dtypes):
            import typing

            try:
                hints = typing.get_type_hints(accumulator.compute_result)
                if "return" in hints:
                    return dt.wrap(hints["return"])
            except Exception:
                pass
            return dt.ANY

        def make_state(self):
            return _CustomAccState(accumulator)

    return _R()


# --- public reducer instances -------------------------------------------------

count = CountReducer()
sum = SumReducer()  # noqa: A001 — mirrors pw.reducers.sum
avg = AvgReducer()
min = _multiset_reducer("min", _finish_min, keyed=False)  # noqa: A001
max = _multiset_reducer("max", _finish_max, keyed=False)  # noqa: A001
argmin = _multiset_reducer("argmin", _finish_argmin, dt.POINTER)
argmax = _multiset_reducer("argmax", _finish_argmax, dt.POINTER)
unique = _multiset_reducer("unique", _finish_unique, keyed=False)
any = _multiset_reducer("any", _finish_any)  # noqa: A001
earliest = EarliestReducer()
latest = LatestReducer()


def sorted_tuple(expr, *, skip_nones: bool = False):
    r"""Aggregate the values of ``expr`` into a sorted tuple per group.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('k | v\na | 3\na | 1\nb | 2')
    >>> r = t.groupby(pw.this.k).reduce(pw.this.k, vs=pw.reducers.sorted_tuple(pw.this.v))
    >>> pw.debug.compute_and_print(r, include_id=False)
    k | vs
    a | (1, 3)
    b | (2,)
    """
    r = _multiset_reducer(
        "sorted_tuple",
        _finish_sorted_tuple_factory(skip_nones),
        lambda ts: dt.List(dt.unoptionalize(ts[0]) if skip_nones else ts[0]),
        keyed=False,
    )
    return r(expr)


def tuple(expr, *, skip_nones: bool = False, sort_by=None):  # noqa: A001
    r"""Aggregate values into a tuple per group, optionally ordered by ``sort_by``.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('k | v | o\na | x | 2\na | y | 1')
    >>> r = t.groupby(pw.this.k).reduce(pw.this.k, vs=pw.reducers.tuple(pw.this.v, sort_by=pw.this.o))
    >>> pw.debug.compute_and_print(r, include_id=False)
    k | vs
    a | ('y', 'x')
    """
    r = _multiset_reducer(
        "tuple",
        _finish_tuple_factory(skip_nones),
        lambda ts: dt.List(dt.unoptionalize(ts[0]) if skip_nones else ts[0]),
    )
    if sort_by is not None:
        return r(expr, sort_by)
    return r(expr)


def ndarray(expr, *, skip_nones: bool = False):
    r = _multiset_reducer(
        "ndarray", _finish_ndarray_factory(skip_nones), dt.ANY_ARRAY
    )
    return r(expr)


# count may be called with zero args inside reduce()
class _CountCallable(CountReducer):
    def __call__(self, *args, **kwargs):
        from pathway_tpu.internals.expression import ReducerExpression

        return ReducerExpression(self, *args)


count = _CountCallable()
