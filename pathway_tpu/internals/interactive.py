"""Interactive live tables.

Parity target: ``/root/reference/python/pathway/internals/interactive.py``
(LiveTable/LiveTableState/LiveTableThread, 222 LoC) and
``internals/table.py:2565`` ``Table.live()``.

``table.live()`` runs the table's sink cone on a background thread (an
export sink through :mod:`export_import`) and returns a ``LiveTable`` —
a real :class:`Table` backed by the exported stream, so it can be both
inspected (``snapshot()``/``__str__``) and composed into further graph
operations that a later ``pw.run()`` executes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any

from pathway_tpu.internals import export_import as ei
from pathway_tpu.internals.table import Table


@dataclass(frozen=True)
class LiveTableSnapshot:
    """Consolidated state of a live table at a frontier."""

    frontier: int
    done: bool
    data: list[tuple[int, tuple]]  # (key, row values)

    def __str__(self) -> str:
        header = (
            "final snapshot" if self.done else f"snapshot at time {self.frontier}"
        )
        return header + "\n" + "\n".join(
            f"  {key:x}: {row}" for key, row in self.data
        )


class LiveTable(Table):
    """A table whose origin graph runs on a background thread.

    Usable like any Table (select/filter/join then ``pw.run()``); also
    inspectable while the origin stream is still running.
    """

    _exported: ei.ExportedTable
    _thread: threading.Thread

    @classmethod
    def _create(cls, origin: Table) -> "LiveTable":
        from pathway_tpu.internals.config import get_config

        if get_config().processes > 1:
            # the background run() would build a second TcpMesh on the
            # same ports as the main run and the two would cross-connect
            raise RuntimeError(
                "Table.live() is single-process only (the live thread "
                "runs its own graph; a multi-process mesh cannot be "
                "shared across two concurrent runs)"
            )
        exported = ei.ExportedTable(origin.schema)

        def attach(lowerer, node):
            return ei._ExportNode(lowerer.scope, node, exported)

        from pathway_tpu.internals.runner import run

        def target():
            try:
                run(_sinks=[("live-export", origin, attach)])
            except BaseException:  # noqa: BLE001 — surfaced via failed()
                exported._finish(failed=True)

        thread = threading.Thread(
            target=target, name=f"pathway:live-{id(origin):x}", daemon=True
        )
        thread.start()

        imported = ei.import_table(exported)
        live = cls(imported.schema, imported._build_fn, universe=imported._universe)
        live._exported = exported
        live._thread = thread
        return live

    # -- inspection ------------------------------------------------------
    def failed(self) -> bool:
        return self._exported.failed

    def frontier(self) -> int:
        return self._exported.frontier()

    def snapshot_at(self, frontier: int) -> LiveTableSnapshot:
        """Consolidate the exported update stream up to ``frontier``."""
        rows, _off = self._exported.data_from_offset(0)
        counts: dict[tuple[int, tuple], int] = {}
        for key, row, time, diff in rows:
            if time <= frontier:
                counts[(key, row)] = counts.get((key, row), 0) + diff
        data = sorted(
            (key, row) for (key, row), c in counts.items() for _ in range(max(c, 0))
        )
        return LiveTableSnapshot(frontier, self._exported.done, data)

    def snapshot(self) -> LiveTableSnapshot:
        return self.snapshot_at(self.frontier())

    def wait_for(self, timeout: float = 10.0) -> "LiveTable":
        """Block until the origin stream finishes (testing/scripting aid)."""
        self._thread.join(timeout)
        return self

    def live(self) -> "LiveTable":
        return self

    def __str__(self) -> str:
        return str(self.snapshot())


class InteractiveModeController:
    """REPL display hook: live tables and snapshots print as their current
    contents instead of ``<object at 0x...>`` (reference
    ``interactive.py:180-203``).  One controller per process; created by
    :func:`enable_interactive_mode`."""

    def __init__(self, _pathway_internal: bool = False):
        assert _pathway_internal, "use pw.enable_interactive_mode()"
        import sys

        self._orig_displayhook = sys.displayhook
        sys.displayhook = self._displayhook

    def _displayhook(self, value: object) -> None:
        if isinstance(value, (LiveTable, LiveTableSnapshot)):
            import builtins

            builtins._ = value
            print(str(value))
        else:
            self._orig_displayhook(value)

    def disable(self) -> None:
        import sys

        sys.displayhook = self._orig_displayhook
        global _interactive_controller
        _interactive_controller = None


_interactive_controller: InteractiveModeController | None = None


def is_interactive_mode_enabled() -> bool:
    return _interactive_controller is not None


def enable_interactive_mode() -> InteractiveModeController:
    """``pw.enable_interactive_mode()`` — experimental, like the reference."""
    import warnings

    global _interactive_controller
    warnings.warn("interactive mode is experimental", stacklevel=2)
    if _interactive_controller is None:
        _interactive_controller = InteractiveModeController(_pathway_internal=True)
    return _interactive_controller
