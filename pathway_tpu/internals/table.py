"""The lazy Table API and its lowering to engine nodes.

Parity targets:
  * ``/root/reference/python/pathway/internals/table.py`` (2,675 LoC) — the
    ~45 public Table methods;
  * ``internals/joins.py`` (1,422), ``internals/groupbys.py`` (402);
  * ``internals/graph_runner/*`` — lowering of operators to engine calls.

Architecture: a ``Table`` is a schema plus a *recipe* — a function from a
``Lowerer`` to an engine ``Node``.  Calling Table methods composes recipes;
``pw.run``/debug helpers instantiate a fresh engine ``Scope`` and lower the
sinks' dependency cones.  Cross-table references inside ``select`` (same
universe) and ``other.ix(expr)`` lookups are both lowered onto the engine's
``IxNode`` so that a change in the *referenced* table correctly retracts and
re-emits dependent rows — the property the reference gets from differential's
join-based column paths.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Mapping, Sequence

from pathway_tpu.engine import dataflow as df
from pathway_tpu.engine.types import (
    ERROR,
    Error,
    Pointer,
    hash_values,
)
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.expression import (
    ColumnExpression,
    ColumnReference,
    ReducerExpression,
)
from pathway_tpu.internals.expression_evaluator import Binder, compile_expr
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.thisclass import ThisPlaceholder, ThisSlice, this

_object_id = id  # `id` is a common keyword parameter below; keep the builtin reachable

# ---------------------------------------------------------------------------
# Universe tracking (universe.py + universe_solver.py analog)
# ---------------------------------------------------------------------------


class Universe:
    _counter = itertools.count()

    def __init__(self, parent: "Universe | None" = None):
        self.id = next(Universe._counter)
        self._parent = parent
        self._equal_root: "Universe" = self
        self._subset_of: set[int] = set()

    def root(self) -> "Universe":
        u = self
        while u._equal_root is not u:
            u = u._equal_root
        if self._equal_root is not u:
            self._equal_root = u
        return u

    def unify(self, other: "Universe") -> None:
        self.root()._equal_root = other.root()

    def is_equal(self, other: "Universe") -> bool:
        return self.root() is other.root()

    def is_subset_of(self, other: "Universe") -> bool:
        if self.is_equal(other):
            return True
        u: Universe | None = self
        seen = set()
        stack = [self.root()]
        while stack:
            cur = stack.pop()
            if cur.id in seen:
                continue
            seen.add(cur.id)
            if cur.is_equal(other):
                return True
            if cur._parent is not None:
                stack.append(cur._parent.root())
            for sid in cur._subset_of:
                stack.append(_universe_registry[sid].root())
        return False

    def promise_subset_of(self, other: "Universe") -> None:
        self._subset_of.add(other.root().id)
        _universe_registry[other.root().id] = other.root()


_universe_registry: dict[int, Universe] = {}


# ---------------------------------------------------------------------------
# Lowerer (GraphRunner analog)
# ---------------------------------------------------------------------------


class Lowerer:
    def __init__(self, scope: df.Scope):
        self.scope = scope
        self.memo: dict[int, df.Node] = {}
        self.pollers: list[Any] = []  # objects with .poll() -> bool(finished)
        self.cleanups: list[Callable[[], None]] = []
        self.persistence_storage: Any = None  # engine.persistence.PersistentStorage
        self._source_counter = 0

    def node(self, table: "Table") -> df.Node:
        key = id(table)
        if key not in self.memo:
            try:
                node = table._build(self)
            except Exception as exc:
                # recipe errors (bad column refs, type mismatches) fire at
                # lowering, far from the user's call — cite their line
                if table._trace_frame is not None:
                    from pathway_tpu.internals.trace import add_trace_note

                    add_trace_note(exc, table._trace_frame)
                raise
            if getattr(node, "user_frame", None) is None:
                node.user_frame = table._trace_frame
            self.memo[key] = node
        return self.memo[key]


# ---------------------------------------------------------------------------
# Special expressions that need the Table layer
# ---------------------------------------------------------------------------


class IxColumnExpression(ColumnExpression):
    """``other.ix(keys).col`` / implicit same-universe foreign reference."""

    __slots__ = ("_data_table", "_key_expr", "_name", "_optional", "_by_id")

    def __init__(self, data_table, key_expr, name, optional=False, by_id=False):
        self._data_table = data_table
        self._key_expr = expr_mod._wrap(key_expr)
        self._name = name
        self._optional = optional
        self._by_id = by_id  # True: implicit same-universe ref (key = row id)

    def _sub_expressions(self):
        return (self._key_expr,)

    def _substitute(self, mapping):
        return IxColumnExpression(
            self._data_table,
            self._key_expr._substitute(mapping),
            self._name,
            self._optional,
            self._by_id,
        )

    def _infer_dtype(self, resolver):
        if self._name == "id":
            base = dt.POINTER
        else:
            col = self._data_table.schema.__columns__.get(self._name)
            base = col.dtype if col else dt.ANY
        return dt.Optional(base) if self._optional else base


class IxRowView:
    """Result of ``table.ix(expr)`` — attribute access yields column exprs."""

    def __init__(self, data_table, key_expr, optional=False):
        self._data_table = data_table
        self._key_expr = key_expr
        self._optional = optional

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return IxColumnExpression(self._data_table, self._key_expr, name, self._optional)

    def __getitem__(self, name):
        if isinstance(name, ColumnReference):
            name = name.name
        return IxColumnExpression(self._data_table, self._key_expr, name, self._optional)

    @property
    def id(self):
        return IxColumnExpression(self._data_table, self._key_expr, "id", self._optional)


class IxAppliedPlaceholder:
    """``pw.this.ix(expr)`` — resolved when bound to a table in select."""

    def __init__(self, base, key_expr, optional=False):
        self._base = base
        self._key_expr = key_expr
        self._optional = optional

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeferredIxColumnExpression(self._key_expr, name, self._optional, ref_args=None)


class IxRefAppliedPlaceholder:
    def __init__(self, base, args, optional=False, instance=None):
        self._base = base
        self._args = args
        self._optional = optional
        self._instance = instance

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeferredIxColumnExpression(
            None, name, self._optional, ref_args=(self._args, self._instance)
        )


class DeferredIxColumnExpression(ColumnExpression):
    """ix on pw.this: the data table is the table select() is called on."""

    __slots__ = ("_key_expr", "_name", "_optional", "_ref_args")

    def __init__(self, key_expr, name, optional, ref_args):
        self._key_expr = expr_mod._wrap(key_expr) if key_expr is not None else None
        self._name = name
        self._optional = optional
        self._ref_args = ref_args

    def _substitute(self, mapping):
        # once we know the concrete table (mapping from `this`), become real
        target = mapping.get(id(this))
        key_expr = (
            self._key_expr._substitute(mapping) if self._key_expr is not None else None
        )
        if target is not None:
            if self._ref_args is not None:
                args, instance = self._ref_args
                args = [expr_mod._wrap(a)._substitute(mapping) for a in args]
                key_expr = expr_mod.PointerExpression(
                    target, *args, optional=self._optional, instance=instance
                )
            return IxColumnExpression(target, key_expr, self._name, self._optional)
        new = DeferredIxColumnExpression(key_expr, self._name, self._optional, self._ref_args)
        return new


# ---------------------------------------------------------------------------
# Binders
# ---------------------------------------------------------------------------


class RowBinder(Binder):
    """Resolves references for expressions evaluated over one table's rows.

    Layout of the evaluation row: the table's columns first, then appended
    regions for each external fetch (same-universe foreign tables and
    ``ix`` lookups), in registration order.
    """

    def __init__(self, lowerer: Lowerer, table: "Table"):
        self.lowerer = lowerer
        self.table = table
        self.col_index = {n: i for i, n in enumerate(table.column_names())}
        self.width = len(self.col_index)
        # fetch registry: fetch_key -> (slot, data_table, key_fn, optional);
        # key_fn None means by-id fetch.  Key expressions are compiled BEFORE
        # the slot is allocated so nested fetches (an ix whose key comes from
        # another fetched column) land earlier in the chain than their users.
        self.fetches: dict[Any, tuple[int, "Table", Any, bool]] = {}
        self.fetch_order: list[Any] = []

    def _fetch_slot(self, data_table, key_expr, optional, by_id) -> tuple[int, "Table"]:
        fk = (id(data_table), repr(key_expr) if key_expr is not None else "@id", optional)
        if fk not in self.fetches:
            key_fn = compile_expr(key_expr, self) if key_expr is not None else None
            if fk not in self.fetches:  # (key compile may have nested same fk)
                slot = self.width
                self.width += len(data_table.column_names()) + 1  # +1 for fetched id
                self.fetches[fk] = (slot, data_table, key_fn, optional)
                self.fetch_order.append(fk)
        return self.fetches[fk][0], data_table

    def resolve(self, ref: ColumnReference):
        tbl = ref.table
        name = ref.name
        if isinstance(tbl, ThisPlaceholder) or tbl is self.table:
            if name == "id":
                return lambda key, row: Pointer(key)
            if name not in self.col_index:
                raise KeyError(
                    f"no column {name!r} in table (columns: {list(self.col_index)})"
                )
            idx = self.col_index[name]
            return lambda key, row: row[idx]
        if isinstance(tbl, Table):
            # same-universe foreign reference — implicit ix by id
            if not tbl._universe.is_equal(self.table._universe) and not self.table._universe.is_subset_of(tbl._universe):
                raise ValueError(
                    f"column {name!r} of a table with a different universe used in "
                    "select; use .ix(...), a join, or promise_universes_are_equal"
                )
            slot, data_table = self._fetch_slot(tbl, None, False, True)
            if name == "id":
                return lambda key, row: row[slot]
            didx = slot + 1 + data_table.column_names().index(name)
            return lambda key, row: row[didx]
        raise ValueError(f"cannot resolve reference {ref!r}")

    def resolve_ix(self, e: IxColumnExpression):
        slot, data_table = self._fetch_slot(
            e._data_table, e._key_expr, e._optional, e._by_id
        )
        if e._name == "id":
            return lambda key, row: row[slot]
        names = data_table.column_names()
        if e._name not in names:
            raise KeyError(f"no column {e._name!r} in ix'd table")
        didx = slot + 1 + names.index(e._name)
        return lambda key, row: row[didx]

    def resolve_dtype(self, ref: ColumnReference) -> dt.DType:
        tbl = ref.table
        if isinstance(tbl, ThisPlaceholder) or tbl is self.table:
            if ref.name == "id":
                return dt.POINTER
            col = self.table.schema.__columns__.get(ref.name)
            return col.dtype if col else dt.ANY
        if isinstance(tbl, Table):
            col = tbl.schema.__columns__.get(ref.name)
            return col.dtype if col else dt.ANY
        return dt.ANY


# patch expression_evaluator's recursion to understand IxColumnExpression
import pathway_tpu.internals.expression_evaluator as _ev  # noqa: E402

_ev_compile_orig = _ev.compile_expr


def _patched_compile(e, binder):
    if isinstance(e, IxColumnExpression) and isinstance(binder, RowBinder):
        return binder.resolve_ix(e)
    return _ev_compile_orig(e, binder)


_ev.compile_expr = _patched_compile
compile_expr = _patched_compile  # use everywhere below


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _desugar(e: Any, table: "Table", extra_map: dict[int, Any] | None = None):
    e = expr_mod._wrap(e)
    mapping = {id(this): table}
    if extra_map:
        mapping.update(extra_map)
    return e._substitute(mapping)


def _infer_dtype(e: ColumnExpression, binder: RowBinder) -> dt.DType:
    try:
        return e._infer_dtype(binder.resolve_dtype)
    except Exception:
        return dt.ANY


def _name_of_expr(e: Any) -> str:
    if isinstance(e, ColumnReference):
        return e.name
    if isinstance(e, IxColumnExpression):
        return e._name
    if isinstance(e, DeferredIxColumnExpression):
        return e._name
    raise ValueError(
        f"cannot infer a column name for expression {e!r}; pass it as name=expression"
    )


def _expand_args(args: Sequence[Any], table: "Table") -> dict[str, Any]:
    """Expand positional select/reduce args (column refs + this-slices)."""
    out: dict[str, Any] = {}
    for a in args:
        if isinstance(a, ThisSlice):
            for n in a._column_names(table):
                out[n] = ColumnReference(this, n)
        elif isinstance(a, TableSlice):
            for n in a.column_names():
                out[n] = ColumnReference(a._table, n)
        elif isinstance(a, Table):
            for n in a.column_names():
                out[n] = ColumnReference(a, n)
        else:
            out[_name_of_expr(a)] = a
    return out


class _IxMerge:
    """merge(row, data_row_with_key) appending (id, *data_columns)."""

    def __init__(self, n_cols):
        self.n_cols = n_cols

    def __call__(self, row, data_row):
        if data_row is None:
            return row + (None,) * (self.n_cols + 1)
        return row + data_row


# IxNode passes raw data rows; wrap data node so fetched region includes id.
class _DataWithIdNode(df.Node):
    name = "with_id_col"
    preserves_append_only = True

    def __init__(self, scope, inp):
        super().__init__(scope, [inp])

    def step(self, time):
        out = []
        for key, row, diff in self.take_pending():
            out.append((key, (Pointer(key),) + row, diff))
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


def _trim_if_needed(lowerer, node: df.Node, binder: "RowBinder", n_cols: int) -> df.Node:
    """Strip fetch-appended columns so output rows match the declared schema."""
    if not binder.fetch_order:
        return node
    return df.ExprNode(lowerer.scope, node, lambda key, row: row[:n_cols])


def _fetch_chain(lowerer, base_node, binder: RowBinder) -> df.Node:
    node = base_node
    for fk in binder.fetch_order:
        slot, data_table, kf, optional = binder.fetches[fk]
        raw_data = lowerer.node(data_table)
        data_node = _DataWithIdNode(lowerer.scope, raw_data).require_state()
        if kf is None:
            key_fn = lambda key, row: key  # noqa: E731
        else:

            def key_fn(key, row, _kf=kf):
                v = _kf(key, row)
                if isinstance(v, Pointer):
                    return v.value
                return v

        node = df.IxNode(
            lowerer.scope,
            node,
            data_node,
            key_fn,
            _IxMerge(len(data_table.column_names())),
            optional=optional,
            strict=not optional,
        )
    return node


# ---------------------------------------------------------------------------
# Joinable base + JoinMode
# ---------------------------------------------------------------------------


import enum


class JoinMode(enum.Enum):
    INNER = 0
    LEFT = 1
    RIGHT = 2
    OUTER = 3


class Joinable:
    def join(self, other, *on, id=None, how=JoinMode.INNER, left_instance=None, right_instance=None):
        """Join with ``other`` on equality conditions; ``how`` picks the join mode.

        Example:

        >>> import pathway_tpu as pw
        >>> t1 = pw.debug.table_from_markdown('''
        ... owner | pet
        ... Alice | dog
        ... Bob   | cat
        ... ''')
        >>> t2 = pw.debug.table_from_markdown('''
        ... pet | sound
        ... dog | woof
        ... cat | meow
        ... ''')
        >>> j = t1.join(t2, t1.pet == t2.pet).select(t1.owner, t2.sound)
        >>> pw.debug.compute_and_print(j, include_id=False)
        owner | sound
        Alice | woof
        Bob   | meow
        """
        return JoinResult(self, other, on, mode=how, id=id)

    def join_inner(self, other, *on, id=None, **kw):
        return JoinResult(self, other, on, mode=JoinMode.INNER, id=id)

    def join_left(self, other, *on, id=None, **kw):
        """Left outer join: unmatched left rows survive with ``None`` fills.

        Example:

        >>> import pathway_tpu as pw
        >>> t1 = pw.debug.table_from_markdown('''
        ... owner | pet
        ... Alice | dog
        ... Eve   | axolotl
        ... ''')
        >>> t2 = pw.debug.table_from_markdown('''
        ... pet | sound
        ... dog | woof
        ... ''')
        >>> j = t1.join_left(t2, t1.pet == t2.pet).select(t1.owner, t2.sound)
        >>> pw.debug.compute_and_print(j, include_id=False)
        owner | sound
        Alice | woof
        Eve   | None
        """
        return JoinResult(self, other, on, mode=JoinMode.LEFT, id=id)

    def join_right(self, other, *on, id=None, **kw):
        return JoinResult(self, other, on, mode=JoinMode.RIGHT, id=id)

    def join_outer(self, other, *on, id=None, **kw):
        return JoinResult(self, other, on, mode=JoinMode.OUTER, id=id)


# ---------------------------------------------------------------------------
# Table
# ---------------------------------------------------------------------------


class Table(Joinable):
    def __init__(
        self,
        schema: type[schema_mod.Schema],
        build: Callable[[Lowerer], df.Node],
        universe: Universe | None = None,
    ):
        self._schema = schema
        self._build_fn = build
        self._universe = universe if universe is not None else Universe()
        _universe_registry[self._universe.id] = self._universe
        # where the user created this table: replayed onto run-time engine
        # errors from operators lowered out of it (reference trace.py)
        from pathway_tpu.internals.trace import user_frame_from_stack

        self._trace_frame = user_frame_from_stack()
        G.new_table(self)

    # -- introspection --
    @property
    def schema(self) -> type[schema_mod.Schema]:
        return self._schema

    def column_names(self) -> list[str]:
        return list(self._schema.__columns__.keys())

    def keys(self):
        return self._schema.__columns__.keys()

    def typehints(self) -> dict[str, Any]:
        return self._schema.typehints()

    @property
    def id(self) -> ColumnReference:
        return ColumnReference(self, "id")

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_") or name in ("schema",):
            raise AttributeError(name)
        if name in self._schema.__columns__:
            return ColumnReference(self, name)
        raise AttributeError(
            f"Table has no column {name!r} (columns: {self.column_names()})"
        )

    def __getitem__(self, arg):
        if isinstance(arg, str):
            if arg == "id":
                return self.id
            if arg not in self._schema.__columns__:
                raise KeyError(arg)
            return ColumnReference(self, arg)
        if isinstance(arg, ColumnReference):
            return ColumnReference(self, arg.name)
        if isinstance(arg, (list, tuple)):
            return TableSlice(self, [c if isinstance(c, str) else c.name for c in arg])
        raise TypeError(f"cannot index Table with {type(arg)}")

    def __iter__(self):
        raise TypeError("Table is not iterable; use pw.debug helpers")

    def __repr__(self):
        cols = ", ".join(
            f"{n}: {c.dtype!r}" for n, c in self._schema.__columns__.items()
        )
        return f"<pw.Table ({cols})>"

    def live(self):
        """Run this table's cone on a background thread and return a
        LiveTable (inspectable while streaming, composable into further
        graph operations).  Experimental — match:
        ``/root/reference/python/pathway/internals/table.py:2565``.
        """
        import warnings

        from pathway_tpu.internals.interactive import LiveTable

        warnings.warn("live tables are an experimental feature", stacklevel=2)
        return LiveTable._create(self)

    @property
    def slice(self) -> "TableSlice":
        return TableSlice(self, self.column_names())

    @property
    def C(self) -> "TableSlice":
        return TableSlice(self, self.column_names())

    def _build(self, lowerer: Lowerer) -> df.Node:
        return self._build_fn(lowerer)

    # -- core ops --
    def select(self, *args, **kwargs) -> "Table":
        """Produce a new table with the given columns (same rows/keys).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> pw.debug.compute_and_print(t.select(pw.this.owner, older=pw.this.age + 1), include_id=False)
        owner | older
        Alice | 6
        Bob   | 4
        Carol | 9
        """
        exprs = _expand_args(args, self)
        exprs.update(kwargs)
        return self._select_impl(exprs, universe=self._universe)

    def _select_impl(self, exprs: Mapping[str, Any], universe: Universe) -> "Table":
        desugared = {n: _desugar(e, self) for n, e in exprs.items()}

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)
            binder = RowBinder(lowerer, self)
            # top-level async UDF columns run through AsyncValuesNode so all
            # rows of an epoch are awaited concurrently (§3.3 semantics);
            # other columns compile to plain row functions
            fns: dict[str, Any] = {}
            async_slot: dict[str, int] = {}
            coro_fns: list = []
            for n, e in desugared.items():
                if isinstance(e, expr_mod.AsyncApplyExpression):
                    arg_fns = [compile_expr(a, binder) for a in e._args]
                    kw_fns = {
                        k: compile_expr(v, binder) for k, v in e._kwargs.items()
                    }
                    fun = e._fun

                    def make_coro(fun=fun, arg_fns=arg_fns, kw_fns=kw_fns):
                        def coro(key, row):
                            return fun(
                                *[f(key, row) for f in arg_fns],
                                **{k: f(key, row) for k, f in kw_fns.items()},
                            )

                        return coro

                    async_slot[n] = len(coro_fns)
                    coro_fns.append(make_coro())
                    fns[n] = None
                else:
                    fns[n] = compile_expr(e, binder)
            node_in = _fetch_chain(lowerer, base, binder)
            async_base = binder.width
            if coro_fns:
                node_in = df.AsyncValuesNode(lowerer.scope, node_in, coro_fns)
            out_dtypes = [new_schema.__columns__[n].dtype for n in fns]

            def fn(key, row, _items=list(fns.items()), _dts=out_dtypes):
                out = []
                for (n, f), d in zip(_items, _dts):
                    if f is None:
                        v = row[async_base + async_slot[n]]
                    else:
                        v = f(key, row)
                    out.append(dt.coerce(v, d))
                return tuple(out)

            node_out = df.ExprNode(lowerer.scope, node_in, fn)
            if not coro_fns and not binder.fetches:
                # columnar fast path: all output expressions must vectorize
                from pathway_tpu.internals import vector_compiler as vc

                vec_fns, needed = [], set()
                for e in desugared.values():
                    # bare column refs skip materialize/rebuild entirely:
                    # the native rebuild copies them from the input row
                    pt = vc.passthrough_index(e, binder)
                    if pt is not None:
                        vec_fns.append(pt)
                        continue
                    compiled = vc.try_compile_vec(e, binder)
                    if compiled is None:
                        vec_fns = None
                        break
                    f_vec, used = compiled
                    vec_fns.append(f_vec)
                    needed |= used
                if vec_fns is not None:
                    node_out.vec_select = (needed, vec_fns, out_dtypes)
            return node_out

        # schema inference
        tmp_binder = RowBinder(Lowerer(df.Scope()), self)
        cols = {}
        for n, e in desugared.items():
            cols[n] = schema_mod.ColumnSchema(name=n, dtype=_infer_dtype(e, tmp_binder))
        new_schema = schema_mod.schema_from_columns(cols)
        return Table(new_schema, build, universe=universe)

    def with_columns(self, *args, **kwargs) -> "Table":
        exprs = {n: ColumnReference(this, n) for n in self.column_names()}
        exprs.update(_expand_args(args, self))
        exprs.update(kwargs)
        return self._select_impl(exprs, universe=self._universe)

    def without(self, *columns) -> "Table":
        """Drop the given columns.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> pw.debug.compute_and_print(t.without(pw.this.age), include_id=False)
        owner | pet
        Alice | dog
        Bob   | cat
        Carol | dog
        """
        names = {c if isinstance(c, str) else c.name for c in columns}
        exprs = {
            n: ColumnReference(this, n) for n in self.column_names() if n not in names
        }
        return self._select_impl(exprs, universe=self._universe)

    def rename(self, names_mapping: dict | None = None, **kwargs) -> "Table":
        """Rename columns (``new=old`` keyword form or a ``{old: new}`` mapping).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> pw.debug.compute_and_print(t.rename(years=pw.this.age).select(pw.this.owner, pw.this.years), include_id=False)
        owner | years
        Alice | 5
        Bob   | 3
        Carol | 8
        """
        if names_mapping:
            return self.rename_by_dict(names_mapping)
        return self.rename_columns(**kwargs)

    def rename_columns(self, **kwargs) -> "Table":
        # new_name=old_ref
        old_of_new = {
            new: (old.name if isinstance(old, ColumnReference) else old)
            for new, old in kwargs.items()
        }
        renamed_olds = set(old_of_new.values())
        exprs: dict[str, Any] = {}
        for n in self.column_names():
            if n in renamed_olds:
                continue
            exprs[n] = ColumnReference(this, n)
        for new, old in old_of_new.items():
            exprs[new] = ColumnReference(this, old)
        return self._select_impl(exprs, universe=self._universe)

    def rename_by_dict(self, names_mapping: Mapping) -> "Table":
        mapping = {
            (k.name if isinstance(k, ColumnReference) else k): v
            for k, v in names_mapping.items()
        }
        exprs: dict[str, Any] = {}
        for n in self.column_names():
            exprs[mapping.get(n, n)] = ColumnReference(this, n)
        return self._select_impl(exprs, universe=self._universe)

    def with_prefix(self, prefix: str) -> "Table":
        return self.rename_by_dict({n: prefix + n for n in self.column_names()})

    def with_suffix(self, suffix: str) -> "Table":
        return self.rename_by_dict({n: n + suffix for n in self.column_names()})

    def filter(self, filter_expression) -> "Table":
        """Keep only the rows satisfying the predicate.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> pw.debug.compute_and_print(t.filter(pw.this.pet == 'dog'), include_id=False)
        owner | pet | age
        Alice | dog | 5
        Carol | dog | 8
        """
        e = _desugar(filter_expression, self)

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)
            binder = RowBinder(lowerer, self)
            pred = compile_expr(e, binder)
            node_in = _fetch_chain(lowerer, base, binder)
            n_cols = len(self.column_names())

            from pathway_tpu.internals import vector_compiler as vc

            vec = None if binder.fetches else vc.try_compile_vec(e, binder)

            class _PredFilter(df.Node):
                name = "filter"
                preserves_append_only = True

                def _try_columnar(self_inner, deltas):
                    f_vec, needed = vec
                    cols = vc.materialize_delta_columns(deltas, needed)
                    if cols is None:
                        vc.note_bail("filter", "dirty-column")
                        return None
                    try:
                        mask = f_vec(cols, len(deltas))
                    except vc.VecBail:
                        vc.note_bail("filter", "value-guard")
                        return None
                    if mask.dtype.kind != "b":
                        vc.note_bail("filter", "result-dtype")
                        return None
                    return vc.filter_deltas(deltas, mask, n_cols)

                def step(self_inner, time):
                    deltas = self_inner.take_pending()
                    out = None
                    if (
                        vec is not None
                        and vc.ENABLED
                        and len(deltas) >= vc.VEC_THRESHOLD
                    ):
                        out = self_inner._try_columnar(deltas)
                    if deltas and vec is not None:
                        if out is None:
                            self_inner.row_batches += 1
                        else:
                            self_inner.vec_batches += 1
                    if out is None:
                        out = []
                        for key, row, diff in deltas:
                            res = pred(key, row)
                            if isinstance(res, Error):
                                continue
                            if res:
                                out.append((key, row[:n_cols], diff))
                    if isinstance(deltas, df.CleanDeltas):
                        out = df.CleanDeltas(out)  # key-subset of clean
                    if self_inner.keep_state:
                        self_inner._update_state(out)
                    self_inner.send(out, time)

            return _PredFilter(lowerer.scope, [node_in])

        return Table(self._schema, build, universe=Universe(parent=self._universe))

    def split(self, split_expression):
        positive = self.filter(split_expression)
        negative = self.filter(~expr_mod._wrap(split_expression))
        return positive, negative

    def copy(self) -> "Table":
        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)

            class _Copy(df.Node):
                name = "copy"
                preserves_append_only = True

            return _Copy(lowerer.scope, [base])

        return Table(self._schema, build, universe=self._universe)

    def flatten(self, to_flatten: ColumnReference, *, origin_id: str | None = None) -> "Table":
        """One output row per element of an iterable column.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pets
        ... Alice | dog,cat
        ... Bob   | fish
        ... ''')
        >>> s = t.select(pw.this.owner, pet=pw.this.pets.str.split(','))
        >>> pw.debug.compute_and_print(s.flatten(pw.this.pet), include_id=False)
        owner | pet
        Alice | cat
        Alice | dog
        Bob   | fish
        """
        col = to_flatten.name
        col_idx = self.column_names().index(col)
        names = self.column_names()

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)

            def fn(key, row, _i=col_idx):
                seq = row[_i]
                if seq is None:
                    return
                if isinstance(seq, str):
                    items = list(seq)
                else:
                    try:
                        items = list(seq)
                    except TypeError:
                        items = [seq]
                for pos, item in enumerate(items):
                    new_key = hash_values([Pointer(key), pos])
                    new_row = row[:_i] + (item,) + row[_i + 1 :]
                    if origin_id is not None:
                        new_row = new_row + (Pointer(key),)
                    yield (new_key, new_row)

            # new keys are hash(origin key, position): pairwise distinct
            node = df.FlattenNode(lowerer.scope, base, fn, key_fresh=True)
            node.vec_flatten = (col_idx, origin_id is not None)
            return node

        cols = dict(self._schema.__columns__)
        inner_t = cols[col].dtype.strip_optional()
        if isinstance(inner_t, dt._List):
            new_t = inner_t.wrapped
        elif isinstance(inner_t, dt._Tuple) and inner_t.args is not Ellipsis:
            new_t = dt.types_lca(*inner_t.args) if len(inner_t.args) > 1 else inner_t.args[0]
        elif inner_t is dt.STR:
            new_t = dt.STR
        else:
            new_t = dt.ANY
        cols[col] = schema_mod.ColumnSchema(name=col, dtype=new_t)
        if origin_id is not None:
            cols[origin_id] = schema_mod.ColumnSchema(name=origin_id, dtype=dt.POINTER)
        return Table(schema_mod.schema_from_columns(cols), build, universe=Universe())

    # -- id manipulation --
    def pointer_from(self, *args, optional: bool = False, instance=None):
        return expr_mod.PointerExpression(self, *args, optional=optional, instance=instance)

    def with_id_from(self, *args, instance=None) -> "Table":
        """Re-key rows from the given expressions (primary-key change).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> r = t.with_id_from(pw.this.owner)
        >>> pw.debug.compute_and_print(r.select(pw.this.owner, pw.this.age), include_id=False)
        owner | age
        Alice | 5
        Bob   | 3
        Carol | 8
        """
        exprs = [_desugar(expr_mod._wrap(a), self) for a in args]
        if instance is not None:
            exprs.append(_desugar(expr_mod._wrap(instance), self))

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)
            binder = RowBinder(lowerer, self)
            fns = [compile_expr(e, binder) for e in exprs]
            node_in = _fetch_chain(lowerer, base, binder)

            def key_fn(key, row):
                return hash_values([f(key, row) for f in fns])

            node = df.ReindexNode(lowerer.scope, node_in, key_fn)
            return _trim_if_needed(lowerer, node, binder, len(self.column_names()))

        return Table(self._schema, build, universe=Universe())

    def with_id(self, new_index: ColumnReference) -> "Table":
        e = _desugar(new_index, self)

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)
            binder = RowBinder(lowerer, self)
            f = compile_expr(e, binder)
            node_in = _fetch_chain(lowerer, base, binder)

            def key_fn(key, row):
                v = f(key, row)
                return v.value if isinstance(v, Pointer) else v

            node = df.ReindexNode(lowerer.scope, node_in, key_fn)
            return _trim_if_needed(lowerer, node, binder, len(self.column_names()))

        return Table(self._schema, build, universe=Universe())

    # -- set ops --
    def _rekey_salted(self, salt: int) -> "Table":
        """Injective deterministic rekey: new id = hash(old id, salt).
        Internal — backs the vectorized sliding-window branches (each
        branch needs distinct, replay-stable keys)."""

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)
            return df.SaltRekeyNode(lowerer.scope, base, salt)

        return Table(self.schema, build, universe=Universe())

    def concat(self, *others: "Table") -> "Table":
        r"""Union of rows of same-schema tables (keys must be disjoint).

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('v\n1\n2')
        >>> b = pw.debug.table_from_markdown('v\n3')
        >>> pw.debug.compute_and_print(a.concat(b), include_id=False)
        v
        2
        3
        """
        tables = [self, *others]
        names = self.column_names()
        for t in others:
            if t.column_names() != names:
                raise ValueError("concat: column sets differ")

        def build(lowerer: Lowerer) -> df.Node:
            nodes = [lowerer.node(t) for t in tables]
            return df.ConcatNode(lowerer.scope, nodes)

        cols = {}
        for n in names:
            merged = self._schema.__columns__[n].dtype
            for t in others:
                merged = dt.types_lca(merged, t._schema.__columns__[n].dtype)
            cols[n] = schema_mod.ColumnSchema(name=n, dtype=merged)
        return Table(schema_mod.schema_from_columns(cols), build, universe=Universe())

    def concat_reindex(self, *others: "Table") -> "Table":
        tables = [self, *others]
        reindexed = [
            t.with_id_from(ColumnReference(this, "id"), instance=i)
            if False
            else t._reindex_tagged(i)
            for i, t in enumerate(tables)
        ]
        return reindexed[0].concat(*reindexed[1:])

    def _reindex_tagged(self, tag: int) -> "Table":
        # same injective hash(Pointer(id), tag) recipe as the sliding
        # branches: the salted-rekey node needs no duplicate-detection
        # state and runs the native C pass
        return self._rekey_salted(tag)

    def update_rows(self, other: "Table") -> "Table":
        r"""Upsert: rows of ``other`` replace/extend rows with the same key.

        Example:

        >>> import pathway_tpu as pw
        >>> old = pw.debug.table_from_markdown('k | v\na | 1\nb | 2', id_from=['k'])
        >>> new = pw.debug.table_from_markdown('k | v\nb | 9\nc | 3', id_from=['k'])
        >>> pw.debug.compute_and_print(old.update_rows(new), include_id=False)
        k | v
        a | 1
        b | 9
        c | 3
        """
        if other.column_names() != self.column_names():
            raise ValueError("update_rows: column sets must match")

        def build(lowerer: Lowerer) -> df.Node:
            return df.UpdateRowsNode(
                lowerer.scope, lowerer.node(self), lowerer.node(other)
            )

        cols = {}
        for n in self.column_names():
            cols[n] = schema_mod.ColumnSchema(
                name=n,
                dtype=dt.types_lca(
                    self._schema.__columns__[n].dtype, other._schema.__columns__[n].dtype
                ),
            )
        return Table(schema_mod.schema_from_columns(cols), build, universe=Universe())

    def update_cells(self, other: "Table") -> "Table":
        r"""Overwrite cells for keys present in ``other`` (same universe or subset).

        Example:

        >>> import pathway_tpu as pw
        >>> old = pw.debug.table_from_markdown('k | v | w\na | 1 | x\nb | 2 | y', id_from=['k'])
        >>> new = pw.debug.table_from_markdown('k | v\nb | 9', id_from=['k'])
        >>> pw.debug.compute_and_print(old.update_cells(new.select(pw.this.v)), include_id=False)
        k | v | w
        a | 1 | x
        b | 9 | y
        """
        extra = set(other.column_names()) - set(self.column_names())
        if extra:
            raise ValueError(f"update_cells: unknown columns {extra}")
        my_names = self.column_names()
        their_names = other.column_names()
        their_pos = {n: i for i, n in enumerate(their_names)}

        def build(lowerer: Lowerer) -> df.Node:
            def merge_fn(lrow, rrow):
                if rrow is None:
                    return lrow
                return tuple(
                    rrow[their_pos[n]] if n in their_pos else lrow[i]
                    for i, n in enumerate(my_names)
                )

            return df.UpdateCellsNode(
                lowerer.scope, lowerer.node(self), lowerer.node(other), merge_fn
            )

        cols = {}
        for n in my_names:
            d = self._schema.__columns__[n].dtype
            if n in their_pos:
                d = dt.types_lca(d, other._schema.__columns__[n].dtype)
            cols[n] = schema_mod.ColumnSchema(name=n, dtype=d)
        return Table(schema_mod.schema_from_columns(cols), build, universe=self._universe)

    def __lshift__(self, other: "Table") -> "Table":
        return self.update_cells(other)

    def intersect(self, *tables: "Table") -> "Table":
        r"""Restrict to rows whose keys appear in every argument table.

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('k | v\nx | 1\ny | 2', id_from=['k'])
        >>> b = pw.debug.table_from_markdown('k | w\ny | 9', id_from=['k'])
        >>> pw.debug.compute_and_print(a.intersect(b), include_id=False)
        k | v
        y | 2
        """
        def build(lowerer: Lowerer) -> df.Node:
            return df.IntersectNode(
                lowerer.scope,
                lowerer.node(self),
                [lowerer.node(t) for t in tables],
            )

        return Table(self._schema, build, universe=Universe(parent=self._universe))

    def difference(self, other: "Table") -> "Table":
        r"""Keep rows whose keys do NOT appear in ``other``.

        Example:

        >>> import pathway_tpu as pw
        >>> a = pw.debug.table_from_markdown('k | v\nx | 1\ny | 2', id_from=['k'])
        >>> b = pw.debug.table_from_markdown('k | w\ny | 9', id_from=['k'])
        >>> pw.debug.compute_and_print(a.difference(b), include_id=False)
        k | v
        x | 1
        """
        def build(lowerer: Lowerer) -> df.Node:
            return df.IntersectNode(
                lowerer.scope,
                lowerer.node(self),
                [lowerer.node(other)],
                difference=True,
            )

        return Table(self._schema, build, universe=Universe(parent=self._universe))

    def restrict(self, other) -> "Table":
        def build(lowerer: Lowerer) -> df.Node:
            return df.IntersectNode(
                lowerer.scope,
                lowerer.node(self),
                [lowerer.node(other)],
            )

        return Table(self._schema, build, universe=other._universe)

    def having(self, *indexers) -> "Table":
        result = self
        for indexer in indexers:
            if isinstance(indexer, ColumnReference):
                data_table = indexer.table
                key_expr = indexer

                def _mk(data_table=data_table, key_expr=key_expr):
                    view = IxRowView(data_table, _desugar(key_expr, self), optional=True)
                    return view.id.is_not_none()

                result = result.filter(_mk())
        return result

    # -- ix --
    def ix(self, expression, *, optional: bool = False, context=None) -> IxRowView:
        """Row lookup by pointer: read columns of the row ``expression`` points at.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... name  | boss
        ... Alice | Carol
        ... Bob   | Carol
        ... Carol | Carol
        ... ''', id_from=['name'])
        >>> r = t.select(pw.this.name, boss_of_boss=t.ix(t.pointer_from(pw.this.boss)).boss)
        >>> pw.debug.compute_and_print(r, include_id=False)
        name  | boss_of_boss
        Alice | Carol
        Bob   | Carol
        Carol | Carol
        """
        return IxRowView(self, expression, optional=optional)

    def ix_ref(self, *args, optional: bool = False, context=None, instance=None) -> IxRowView:
        key_expr = expr_mod.PointerExpression(self, *args, optional=optional, instance=instance)
        return IxRowView(self, key_expr, optional=optional)

    # -- groupby / reduce --
    def groupby(self, *args, id=None, sort_by=None, instance=None, **kwargs) -> "GroupedTable":
        """Group rows by the given expressions; follow with ``.reduce(...)``.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> res = t.groupby(pw.this.pet).reduce(
        ...     pw.this.pet,
        ...     n=pw.reducers.count(),
        ...     oldest=pw.reducers.max(pw.this.age),
        ... )
        >>> pw.debug.compute_and_print(res, include_id=False)
        pet | n | oldest
        cat | 1 | 3
        dog | 2 | 8
        """
        return GroupedTable(self, args, id=id, sort_by=sort_by, instance=instance)

    def reduce(self, *args, **kwargs) -> "Table":
        """Reduce the whole table to a single row of aggregates.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> pw.debug.compute_and_print(t.reduce(total_age=pw.reducers.sum(pw.this.age)), include_id=False)
        total_age
        16
        """
        return GroupedTable(self, (), id=None).reduce(*args, **kwargs)

    def deduplicate(
        self,
        *,
        value=None,
        instance=None,
        acceptor: Callable[[Any, Any], bool] | None = None,
        persistent_id: str | None = None,
        name: str | None = None,
    ) -> "Table":
        """Keep one accepted row per ``instance``; ``acceptor`` decides replacement.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... k | v  | _time
        ... a | 1  | 2
        ... a | 5  | 4
        ... a | 2  | 6
        ... ''')
        >>> res = t.deduplicate(value=pw.this.v, instance=pw.this.k, acceptor=lambda new, old: new > old)
        >>> pw.debug.compute_and_print(res.select(pw.this.v), include_id=False)
        v
        5
        """
        if value is None:
            raise ValueError("deduplicate requires value=")
        if acceptor is None:
            acceptor = lambda new, old: True  # noqa: E731
        value_e = _desugar(expr_mod._wrap(value), self)
        inst_e = _desugar(expr_mod._wrap(instance), self) if instance is not None else None

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)
            binder = RowBinder(lowerer, self)
            vf = compile_expr(value_e, binder)
            inf = compile_expr(inst_e, binder) if inst_e is not None else None
            node_in = _fetch_chain(lowerer, base, binder)
            n_cols = len(self.column_names())

            def instance_fn(key, row):
                return inf(key, row) if inf is not None else ()

            def value_fn(key, row):
                return vf(key, row)

            def out_key_fn(inst):
                return hash_values([inst])

            node = df.DeduplicateNode(
                lowerer.scope, node_in, instance_fn, value_fn,
                lambda new, old: acceptor(new, old) if old is not None else True,
                out_key_fn,
            )

            def trim_fn(key, row):
                return row[:n_cols]

            return df.ExprNode(lowerer.scope, node, trim_fn)

        return Table(self._schema, build, universe=Universe())

    # -- sort --
    def sort(self, key, instance=None) -> "Table":
        """Add ``prev``/``next`` pointer columns reflecting the sort order.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown('''
        ... owner | pet  | age
        ... Alice | dog  | 5
        ... Bob   | cat  | 3
        ... Carol | dog  | 8
        ... ''')
        >>> s = t.sort(key=pw.this.age)
        >>> r = t.select(pw.this.owner, next_owner=t.ix(s.next, optional=True).owner)
        >>> pw.debug.compute_and_print(r, include_id=False)
        owner | next_owner
        Alice | Carol
        Bob   | Alice
        Carol | None
        """
        key_e = _desugar(expr_mod._wrap(key), self)
        inst_e = _desugar(expr_mod._wrap(instance), self) if instance is not None else None

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)
            binder = RowBinder(lowerer, self)
            kf = compile_expr(key_e, binder)
            inf = compile_expr(inst_e, binder) if inst_e is not None else None
            node_in = _fetch_chain(lowerer, base, binder)
            return df.SortNode(
                lowerer.scope,
                node_in,
                lambda key, row: kf(key, row),
                (lambda key, row: inf(key, row)) if inf is not None else (lambda key, row: ()),
            )

        cols = {
            "prev": schema_mod.ColumnSchema(name="prev", dtype=dt.Optional(dt.POINTER)),
            "next": schema_mod.ColumnSchema(name="next", dtype=dt.Optional(dt.POINTER)),
        }
        return Table(schema_mod.schema_from_columns(cols), build, universe=self._universe)

    def diff(self, timestamp, *values, instance=None) -> "Table":
        from pathway_tpu.stdlib.ordered import diff as _diff

        return _diff(self, timestamp, *values, instance=instance)

    # -- typing ops --
    def cast_to_types(self, **kwargs) -> "Table":
        exprs: dict[str, Any] = {
            n: ColumnReference(this, n) for n in self.column_names()
        }
        for n, t in kwargs.items():
            exprs[n] = expr_mod.cast(t, ColumnReference(this, n))
        return self._select_impl(exprs, universe=self._universe)

    @staticmethod
    def empty(**kwargs) -> "Table":
        """An empty table with the schema given by column-name → type kwargs
        (reference table.py:355).

        Example:

        >>> import pathway_tpu as pw
        >>> t1 = pw.Table.empty(age=float, pet=float)
        >>> pw.debug.compute_and_print(t1, include_id=False)
        age | pet
        """
        from pathway_tpu.io._utils import make_static_input_table

        return make_static_input_table(
            schema_mod.schema_from_types(**kwargs), []
        )

    @staticmethod
    def from_columns(*args, **kwargs) -> "Table":
        """Build a table from same-universe columns, optionally renamed via
        kwargs (reference table.py:265).

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown("a | b\\n1 | 2")
        >>> t2 = pw.Table.from_columns(t.a, bb=t.b)
        >>> pw.debug.compute_and_print(t2, include_id=False)
        a | bb
        1 | 2
        """
        refs: list[tuple[str, ColumnReference]] = []
        for ref in args:
            refs.append((ref.name, ref))
        for name, ref in kwargs.items():
            refs.append((name, ref))
        if not refs:
            raise ValueError("from_columns requires at least one column")
        names = [n for (n, _r) in refs]
        if len(set(names)) != len(names):
            # silent last-wins would drop a requested column
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"from_columns: duplicate column names {dupes}")
        base = refs[0][1].table
        for _n, r in refs[1:]:
            # is_equal honors promise_are_equal unification, unlike identity
            if not r.table._universe.is_equal(base._universe):
                raise ValueError(
                    "from_columns: all columns must share one universe"
                )
        return base.select(**{n: r for (n, r) in refs})

    def update_id_type(self, id_type, *, id_append_only: bool | None = None) -> "Table":
        """Declare the id column's Pointer type (reference table.py:2003).
        Row keys here are untyped 128-bit hashes, so this is a typing-level
        declaration: it validates the type and returns the same rows."""
        wrapped = dt.wrap(id_type)
        if not (wrapped is dt.POINTER or isinstance(wrapped, dt._Pointer)):
            raise TypeError(f"update_id_type expects a Pointer type, got {id_type!r}")
        return self.copy()

    def eval_type(self, expression) -> "dt.DType":
        """The dtype ``expression`` evaluates to in this table's context
        (reference table.py:2549).  Unknown column references raise;
        operator typing follows this build's (lenient) interpreter.

        Example:

        >>> import pathway_tpu as pw
        >>> t = pw.debug.table_from_markdown("a | b\\n1 | 2")
        >>> t.eval_type(t.a + t.b)
        INT
        """
        wrapped = expr_mod._wrap(expression)
        self._validate_column_refs(wrapped)
        tmp_binder = RowBinder(Lowerer(df.Scope()), self)
        return _infer_dtype(wrapped, tmp_binder)

    def _validate_column_refs(self, e) -> None:
        """Raise KeyError for refs to columns this table does not have —
        the silent ANY fallback of dtype inference must not hide typos in
        the public introspection API."""
        if isinstance(e, ColumnReference):
            tbl = e.table
            if isinstance(tbl, ThisPlaceholder) or tbl is self:
                if e.name != "id" and e.name not in self._schema.__columns__:
                    raise KeyError(
                        f"no column {e.name!r} in this table "
                        f"(has {self.column_names()})"
                    )
            return
        for attr in getattr(e, "__slots__", ()):
            try:
                v = getattr(e, attr)
            except AttributeError:
                continue
            if isinstance(v, ColumnExpression):
                self._validate_column_refs(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, ColumnExpression):
                        self._validate_column_refs(x)

    def update_types(self, **kwargs) -> "Table":
        new_schema = self._schema.update_types(**kwargs)

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)

            class _Retype(df.Node):
                name = "update_types"
                preserves_append_only = True

            return _Retype(lowerer.scope, [base])

        return Table(new_schema, build, universe=self._universe)

    def remove_errors(self) -> "Table":
        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(self)

            def pred(key, row):
                return not any(isinstance(v, Error) for v in row)

            return df.FilterNode(lowerer.scope, base, pred)

        return Table(self._schema, build, universe=Universe(parent=self._universe))

    def await_futures(self) -> "Table":
        return self.copy()

    # -- universe promises --
    def promise_universes_are_equal(self, other: "Table") -> "Table":
        self._universe.unify(other._universe)
        return self

    def promise_universes_are_disjoint(self, other: "Table") -> "Table":
        return self

    def promise_universe_is_subset_of(self, other: "Table") -> "Table":
        self._universe.promise_subset_of(other._universe)
        return self

    def promise_universe_is_equal_to(self, other: "Table") -> "Table":
        self._universe.unify(other._universe)
        return self

    def with_universe_of(self, other: "Table") -> "Table":
        t = self.copy()
        t._universe = other._universe
        return t

    def is_universe_equal(self, other: "Table") -> bool:
        return self._universe.is_equal(other._universe)

    # -- engine hooks used by stdlib (reference table.py:584-725) --
    def _external_index_as_of_now(
        self,
        index_factory,
        query_table: "Table",
        index_column: ColumnReference,
        query_column: ColumnReference,
        *,
        index_filter_data_column: ColumnReference | None = None,
        query_filter_column: ColumnReference | None = None,
        query_number_of_matches=None,
        query_metadata_column=None,
        res_type=None,
    ) -> "Table":
        data_col_idx = self.column_names().index(index_column.name)
        q_names = query_table.column_names()
        q_col_idx = q_names.index(query_column.name)
        filt_idx = (
            self.column_names().index(index_filter_data_column.name)
            if index_filter_data_column is not None
            else None
        )
        q_filt_idx = (
            q_names.index(query_filter_column.name)
            if query_filter_column is not None
            else None
        )
        q_k_idx = None
        if query_number_of_matches is not None and isinstance(
            query_number_of_matches, ColumnReference
        ):
            q_k_idx = q_names.index(query_number_of_matches.name)
        default_k = (
            query_number_of_matches
            if isinstance(query_number_of_matches, int)
            else None
        )

        def build(lowerer: Lowerer) -> df.Node:
            data_node = lowerer.node(self)
            query_node = lowerer.node(query_table)
            index = index_factory.build()

            class _Idx:
                def add(self, key, row):
                    index.add(
                        key,
                        row[data_col_idx],
                        row[filt_idx] if filt_idx is not None else None,
                    )

                def remove(self, key):
                    index.remove(key)

                def search(self, qrow):
                    k = qrow[q_k_idx] if q_k_idx is not None else default_k
                    return index.search(
                        qrow[q_col_idx],
                        k,
                        qrow[q_filt_idx] if q_filt_idx is not None else None,
                    )

                def search_many(self, qrows):
                    # one bucketed device dispatch per epoch when the
                    # inner index batches (stdlib/indexing KNN does)
                    reqs = [
                        (
                            qrow[q_col_idx],
                            qrow[q_k_idx] if q_k_idx is not None else default_k,
                            qrow[q_filt_idx] if q_filt_idx is not None else None,
                        )
                        for qrow in qrows
                    ]
                    many = getattr(index, "search_many", None)
                    if many is not None:
                        return many(reqs)
                    return [index.search(*req) for req in reqs]

            def res_fn(qkey, qrow, result):
                # result: list[(data_key, score)]
                return (tuple((Pointer(k), s) for k, s in result),)

            return df.ExternalIndexNode(lowerer.scope, data_node, query_node, _Idx(), res_fn)

        cols = {
            "_pw_index_reply": schema_mod.ColumnSchema(
                name="_pw_index_reply",
                dtype=dt.List(dt.Tuple(dt.POINTER, dt.FLOAT)),
            )
        }
        return Table(
            schema_mod.schema_from_columns(cols), build, universe=query_table._universe
        )

    def _gradual_broadcast(self, threshold_table, lower_column, value_column, upper_column) -> "Table":
        names = threshold_table.column_names()
        li, vi, ui = (
            names.index(lower_column.name),
            names.index(value_column.name),
            names.index(upper_column.name),
        )

        def build(lowerer: Lowerer) -> df.Node:
            def lvu_fn(key, row):
                return (row[li], row[vi], row[ui])

            return df.GradualBroadcastNode(
                lowerer.scope, lowerer.node(self), lowerer.node(threshold_table), lvu_fn
            )

        cols = dict(self._schema.__columns__)
        cols["_pw_value"] = schema_mod.ColumnSchema(name="_pw_value", dtype=dt.FLOAT)
        return Table(
            schema_mod.schema_from_columns(cols), build, universe=self._universe
        )

    def _buffer(self, threshold_column, time_column) -> "Table":
        return self._temporal_op(threshold_column, time_column, df.BufferNode)

    def _freeze(self, threshold_column, time_column) -> "Table":
        return self._temporal_op(threshold_column, time_column, df.FreezeNode)

    def _forget(self, threshold_column, time_column, mark_forgetting_records: bool = False) -> "Table":
        return self._temporal_op(threshold_column, time_column, df.ForgetNode)

    def _temporal_op(self, threshold_column, time_column, node_cls) -> "Table":
        thr_e = _desugar(expr_mod._wrap(threshold_column), self)
        time_e = _desugar(expr_mod._wrap(time_column), self)

        def build(lowerer: Lowerer) -> df.Node:
            from pathway_tpu.internals import vector_compiler as vc

            base = lowerer.node(self)
            binder = RowBinder(lowerer, self)
            tf = compile_expr(time_e, binder)
            thf = compile_expr(thr_e, binder)
            node_in = _fetch_chain(lowerer, base, binder)
            node = node_cls(lowerer.scope, node_in, tf, thf)
            # columnar spec: window behaviors lower their time/threshold
            # math to column ± const, so the whole epoch batch's pane
            # admit/expiry arithmetic can run as array ops (the node bails
            # back to tf/thf — the oracle — on anything the arrays cannot
            # honor exactly)
            spec_t = vc.affine_index(time_e, binder)
            spec_thr = vc.affine_index(thr_e, binder)
            if spec_t is not None and spec_thr is not None:
                node.vec_temporal = (*spec_t, *spec_thr)
            return _trim_if_needed(lowerer, node, binder, len(self.column_names()))

        return Table(self._schema, build, universe=Universe(parent=self._universe))

    # -- output --
    def to(self, sink) -> None:
        sink.write(self)

    def debug(self, name: str) -> "Table":
        from pathway_tpu.internals.runner import add_debug_sink

        add_debug_sink(name, self)
        return self

    def _subscribe_raw(self, on_data, on_time_end=None, on_end=None, keep_state=False, name="subscribe"):
        """Register a raw sink; on_data(key, row, time, diff)."""

        def attach(lowerer: Lowerer, node: df.Node):
            out = df.OutputNode(
                lowerer.scope, node, on_data=on_data, on_time_end=on_time_end, on_end=on_end
            )
            if keep_state:
                out.require_state()
            return out

        G.add_sink(name, self, attach)


# ---------------------------------------------------------------------------
# TableSlice
# ---------------------------------------------------------------------------


class TableSlice:
    def __init__(self, table: Table, names: list[str]):
        self._table = table
        self._names = names

    def column_names(self) -> list[str]:
        return self._names

    def keys(self):
        return self._names

    def without(self, *cols) -> "TableSlice":
        drop = {c if isinstance(c, str) else c.name for c in cols}
        return TableSlice(self._table, [n for n in self._names if n not in drop])

    def with_prefix(self, prefix: str) -> "TableSlice":
        return self.rename({n: prefix + n for n in self._names})

    def with_suffix(self, suffix: str) -> "TableSlice":
        return self.rename({n: n + suffix for n in self._names})

    def rename(self, mapping: Mapping) -> "TableSlice":
        # produces a slice carrying rename info; materialized via select
        new = TableSlice(self._table, list(self._names))
        new._renames = {  # type: ignore[attr-defined]
            (k.name if isinstance(k, ColumnReference) else k): v for k, v in mapping.items()
        }
        return new

    def __iter__(self):
        return iter(ColumnReference(self._table, n) for n in self._names)

    def __getitem__(self, name):
        if isinstance(name, ColumnReference):
            name = name.name
        return ColumnReference(self._table, name)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._names:
            return ColumnReference(self._table, name)
        raise AttributeError(name)

    @property
    def id(self):
        return ColumnReference(self._table, "id")


# ---------------------------------------------------------------------------
# GroupedTable
# ---------------------------------------------------------------------------


class GroupedTable:
    def __init__(self, table: Table, grouping: Sequence[Any], id=None, sort_by=None, instance=None):
        self._table = table
        self._id_param = id
        self._instance = instance
        self._sort_by = sort_by
        gcols: list[ColumnReference] = []
        for g in grouping:
            if isinstance(g, ColumnReference):
                gcols.append(g)
            elif isinstance(g, str):
                gcols.append(ColumnReference(this, g))
            else:
                raise TypeError(f"groupby expects column references, got {type(g)}")
        if id is not None:
            # groupby(id=t.id) groups by row id
            gcols = [id if isinstance(id, ColumnReference) else ColumnReference(this, "id")]
        self._gcols = gcols

    def reduce(self, *args, **kwargs) -> Table:
        table = self._table
        exprs = _expand_args(args, table)
        exprs.update(kwargs)
        desugared = {n: _desugar(expr_mod._wrap(e), table) for n, e in exprs.items()}
        g_exprs = [_desugar(g, table) for g in self._gcols]
        inst_expr = (
            _desugar(expr_mod._wrap(self._instance), table)
            if self._instance is not None
            else None
        )
        g_names = [g.name if isinstance(g, ColumnReference) else None for g in self._gcols]
        grouped_by_id = self._id_param is not None

        # split each output expression into reducer slots + outer expr
        slots: list[ReducerExpression] = []

        class _SlotRef(ColumnReference):
            # subclassing ColumnReference routes nested slots through the
            # evaluator's binder.resolve path
            __slots__ = ("_slot",)

            def __init__(self, slot):
                super().__init__(None, f"__slot_{slot}__")
                self._slot = slot

            def _substitute(self, mapping):
                return self

            def _infer_dtype(self, resolver):
                return resolver(self)

        def extract_reducers(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ReducerExpression):
                slots.append(e)
                return _SlotRef(len(slots) - 1)
            subs = list(e._sub_expressions())
            if not subs:
                return e
            # rebuild via substitute trick: substitute doesn't handle this case,
            # so walk manually for known composite types
            new = e._substitute({})
            # replace sub-expressions in the rebuilt copy
            _replace_subs(new, extract_reducers)
            return new

        def _replace_subs(e, fn):
            for attr in getattr(e, "__slots__", ()):  # mutate in place
                try:
                    v = getattr(e, attr)
                except AttributeError:
                    continue
                if isinstance(v, ReducerExpression):
                    slots.append(v)
                    object.__setattr__(e, attr, _SlotRef(len(slots) - 1))
                elif isinstance(v, ColumnExpression):
                    _replace_subs(v, fn)
                elif isinstance(v, tuple) and any(isinstance(x, ColumnExpression) for x in v):
                    new_items = []
                    for x in v:
                        if isinstance(x, ReducerExpression):
                            slots.append(x)
                            new_items.append(_SlotRef(len(slots) - 1))
                        else:
                            if isinstance(x, ColumnExpression):
                                _replace_subs(x, fn)
                            new_items.append(x)
                    object.__setattr__(e, attr, tuple(new_items))
                elif isinstance(v, dict):
                    for k2, x in list(v.items()):
                        if isinstance(x, ReducerExpression):
                            slots.append(x)
                            v[k2] = _SlotRef(len(slots) - 1)
                        elif isinstance(x, ColumnExpression):
                            _replace_subs(x, fn)

        outer_exprs: dict[str, ColumnExpression] = {}
        for n, e in desugared.items():
            if isinstance(e, ReducerExpression):
                slots.append(e)
                outer_exprs[n] = _SlotRef(len(slots) - 1)
            else:
                copy = e._substitute({})
                _replace_subs(copy, extract_reducers)
                outer_exprs[n] = copy

        n_group = len(g_exprs) + (1 if inst_expr is not None else 0)

        class GroupBinder(Binder):
            """Resolves refs over the synthetic (gk..., slot values...) row."""

            def __init__(self, inner_binder):
                self.inner = inner_binder

            def resolve(self, ref):
                if isinstance(ref, _SlotRef):
                    idx = n_group + ref._slot
                    return lambda key, row: row[idx]
                name = ref.name
                if grouped_by_id and name == "id":
                    return lambda key, row: row[0]
                if name in g_names:
                    idx = g_names.index(name)
                    return lambda key, row: row[idx]
                if name == "id":
                    return lambda key, row: Pointer(key)
                raise KeyError(
                    f"column {name!r} used in reduce() is not a grouping column; "
                    "wrap it in a reducer"
                )

            def resolve_dtype(self, ref):
                return self.inner.resolve_dtype(ref)

        # patch compile for _SlotRef
        def compile_group_expr(e, gbinder):
            if isinstance(e, _SlotRef):
                return gbinder.resolve(e)
            if isinstance(e, ColumnReference):
                return gbinder.resolve(e)
            # recurse via evaluator with gbinder as Binder
            return compile_expr(e, gbinder)

        def build(lowerer: Lowerer) -> df.Node:
            base = lowerer.node(table)
            binder = RowBinder(lowerer, table)
            g_fns = [compile_expr(g, binder) for g in g_exprs]
            inst_fn = compile_expr(inst_expr, binder) if inst_expr is not None else None
            reducer_specs = []
            for r in slots:
                arg_fns = [compile_expr(a, binder) for a in r._args]
                if not arg_fns:
                    reducer_specs.append((r._reducer, lambda key, row: ()))
                else:
                    reducer_specs.append(
                        (
                            r._reducer,
                            (lambda fns: lambda key, row: tuple(f(key, row) for f in fns))(
                                arg_fns
                            ),
                        )
                    )
            node_in = _fetch_chain(lowerer, base, binder)

            def group_key_fn(key, row):
                gk = tuple(f(key, row) for f in g_fns)
                if grouped_by_id:
                    gk = (Pointer(key),)
                if inst_fn is not None:
                    gk = gk + (inst_fn(key, row),)
                return gk

            def out_key_fn(gk):
                if grouped_by_id:
                    return gk[0].value
                return hash_values(list(gk))

            gbinder = GroupBinder(binder)
            out_fns = [
                compile_group_expr(e, gbinder) for e in outer_exprs.values()
            ]
            out_dtypes = [new_schema.__columns__[n].dtype for n in outer_exprs]

            def result_fn(gk, vals):
                row = tuple(gk) + tuple(vals)
                okey = out_key_fn(gk)
                return tuple(
                    dt.coerce(f(okey, row), d) for f, d in zip(out_fns, out_dtypes)
                )

            gb_node = df.GroupByNode(
                lowerer.scope,
                node_in,
                group_key_fn,
                out_key_fn,
                reducer_specs,
                result_fn,
            )
            gb_node.vec_group = _vec_group_spec(
                g_exprs, inst_expr, grouped_by_id, slots, binder
            )
            key_idxs = _group_key_idxs(g_exprs, inst_expr, grouped_by_id, binder)
            if key_idxs is not None:
                # batched exchange routing: the group route key is
                # hash_values over exactly these column values, so the
                # per-row route loop collapses to one native pass
                # (hash_none=True: group keys hash Nones like any value)
                gb_node.exchange_route_cols = {0: (key_idxs, True)}
            return gb_node

        def _plain_col_idx(e, binder):
            from pathway_tpu.internals.thisclass import ThisPlaceholder

            if not isinstance(e, ColumnReference):
                return None
            if not (isinstance(e.table, ThisPlaceholder) or e.table is binder.table):
                return None
            if e.name == "id" or e.name not in binder.col_index:
                return None
            return binder.col_index[e.name]

        def _group_key_idxs(g_exprs, inst_expr, grouped_by_id, binder):
            """Column indices whose row values ARE the group key tuple (in
            group-key order, instance last) — None when any key is not a
            plain same-table column."""
            if grouped_by_id or not g_exprs:
                return None
            idxs = [_plain_col_idx(e, binder) for e in g_exprs]
            if inst_expr is not None:
                idxs.append(_plain_col_idx(inst_expr, binder))
            if any(i is None for i in idxs):
                return None
            return tuple(idxs)

        def _vec_group_spec(g_exprs, inst_expr, grouped_by_id, slots, binder):
            """Columnar groupby spec (GroupByNode.vec_group) when the shape
            allows it: plain grouping columns (instance included — it is
            just one more key column), count/sum/avg/min/max reducers over
            plain columns.  Anything else keeps the row path."""
            from pathway_tpu.internals.reducers import (
                AvgReducer,
                CountReducer,
                SumReducer,
            )

            def plain_idx(e):
                return _plain_col_idx(e, binder)

            g_idxs = _group_key_idxs(g_exprs, inst_expr, grouped_by_id, binder)
            if g_idxs is None:
                return None
            # single-column groups keep the scalar spec (numpy unique /
            # native raw grouping); multi-column groups hash-group tuples
            gidx = g_idxs[0] if len(g_idxs) == 1 else g_idxs
            red_cols = []
            for r in slots:
                red = r._reducer
                # isinstance: count is exported as a _CountCallable subclass
                if isinstance(red, CountReducer) and not r._args:
                    red_cols.append(("count", None))
                    continue
                if type(red) in (SumReducer, AvgReducer) and len(r._args) == 1:
                    vidx = plain_idx(r._args[0])
                    if vidx is not None:
                        red_cols.append(("sum", vidx))
                        continue
                from pathway_tpu.internals import reducers as _red_mod

                # identity against the public singletons: a user reducer
                # merely NAMED "min" must not be routed to the mm path
                if red in (_red_mod.min, _red_mod.max) and len(r._args) == 1:
                    vidx = plain_idx(r._args[0])
                    if vidx is not None:
                        # multiset pair update; extraction stays in the state
                        red_cols.append(("mm", vidx))
                        continue
                return None
            return (gidx, red_cols)

        # schema inference
        tmp_binder = RowBinder(Lowerer(df.Scope()), table)
        gb = None

        def type_resolver(ref):
            if isinstance(ref, _SlotRef):
                return slots[ref._slot]._infer_dtype(tmp_binder.resolve_dtype)
            return tmp_binder.resolve_dtype(ref)

        cols = {}
        for n, e in outer_exprs.items():
            try:
                cols[n] = schema_mod.ColumnSchema(name=n, dtype=e._infer_dtype(type_resolver))
            except Exception:
                cols[n] = schema_mod.ColumnSchema(name=n, dtype=dt.ANY)
        new_schema = schema_mod.schema_from_columns(cols)
        universe = table._universe if grouped_by_id else Universe()
        return Table(new_schema, build, universe=universe)


# ---------------------------------------------------------------------------
# JoinResult
# ---------------------------------------------------------------------------


from pathway_tpu.internals.thisclass import left as left_ph, right as right_ph


class JoinResult(Joinable):
    def __init__(self, left_t, right_t, on: Sequence[Any], mode: JoinMode, id=None):
        # left_t/right_t may be JoinResult (chained joins): materialize first
        if isinstance(left_t, JoinResult):
            left_t = left_t._as_table()
        if isinstance(right_t, JoinResult):
            right_t = right_t._as_table()
        self._left = left_t
        self._right = right_t
        self._mode = mode
        self._id_param = id
        self._left_on: list[ColumnExpression] = []
        self._right_on: list[ColumnExpression] = []
        for cond in on:
            if not isinstance(cond, expr_mod.ColumnBinaryOpExpression) or cond._op != "==":
                raise ValueError("join conditions must be equalities (a == b)")
            l_e, r_e = cond._left, cond._right
            if self._refers(r_e, self._left) and self._refers(l_e, self._right):
                l_e, r_e = r_e, l_e
            self._left_on.append(
                l_e._substitute({_object_id(left_ph): self._left, _object_id(this): self._left})
            )
            self._right_on.append(
                r_e._substitute({_object_id(right_ph): self._right, _object_id(this): self._right})
            )

    @staticmethod
    def _refers(e: ColumnExpression, table: Table) -> bool:
        if isinstance(e, ColumnReference):
            if e.table is table:
                return True
            if isinstance(e.table, ThisPlaceholder):
                return False
        for sub in e._sub_expressions():
            if JoinResult._refers(sub, table):
                return True
        return False

    @staticmethod
    def _side_of(tbl, left_table, right_table) -> str | None:
        """'left'/'right'/None — the ONE left/right/ThisPlaceholder
        dispatch rule shared by out_key_fn, the native okey-mode
        detection and the projection spec (they must never desync)."""
        if tbl is left_table or (
            isinstance(tbl, ThisPlaceholder) and tbl._kind == "left"
        ):
            return "left"
        if tbl is right_table or (
            isinstance(tbl, ThisPlaceholder) and tbl._kind == "right"
        ):
            return "right"
        return None

    def _lower_join(self, lowerer: Lowerer) -> df.JoinNode:
        lnode = lowerer.node(self._left)
        rnode = lowerer.node(self._right)
        lbinder = RowBinder(lowerer, self._left)
        rbinder = RowBinder(lowerer, self._right)
        l_fns = [compile_expr(e, lbinder) for e in self._left_on]
        r_fns = [compile_expr(e, rbinder) for e in self._right_on]
        lnode = _fetch_chain(lowerer, lnode, lbinder)
        rnode = _fetch_chain(lowerer, rnode, rbinder)

        def none_guard(fns):
            def f(key, row):
                vals = tuple(fn(key, row) for fn in fns)
                if any(v is None or isinstance(v, Error) for v in vals):
                    return None  # null join keys never match (SQL semantics)
                return vals

            return f

        id_param = self._id_param
        left_table, right_table = self._left, self._right

        id_side = None
        if (
            id_param is not None
            and isinstance(id_param, ColumnReference)
            and id_param.name == "id"
        ):
            id_side = JoinResult._side_of(id_param.table, left_table, right_table)

        def out_key_fn(lkey, rkey, jk):
            if id_side == "left":
                return lkey if lkey is not None else hash_values([None, rkey])
            if id_side == "right":
                return rkey if rkey is not None else hash_values([lkey, None])
            return hash_values(
                [
                    Pointer(lkey) if lkey is not None else None,
                    Pointer(rkey) if rkey is not None else None,
                ]
            )

        node = df.JoinNode(
            lowerer.scope,
            lnode,
            rnode,
            none_guard(l_fns),
            none_guard(r_fns),
            out_key_fn,
            left_outer=self._mode in (JoinMode.LEFT, JoinMode.OUTER),
            right_outer=self._mode in (JoinMode.RIGHT, JoinMode.OUTER),
        )
        from pathway_tpu.internals import vector_compiler as vc

        # plain-column equi-joins run the whole delta-join step in the
        # native C++ index (reference join hot path, dataflow.rs:2740);
        # okey modes mirror out_key_fn above exactly.  Outer modes are
        # supported for the default hash-pair out keys (modes 1/2 with
        # a nullable counterpart keep the row path: their null-pad key
        # derivation serializes the RAW key, a distinct recipe).
        l_idxs = [vc.passthrough_index(e, lbinder) for e in self._left_on]
        r_idxs = [vc.passthrough_index(e, rbinder) for e in self._right_on]

        def _hashable_key_dtypes() -> bool:
            """The native index matches by serialized bytes; the row
            path by Python equality.  They agree only for same-dtype
            keys whose equality is byte equality: int/str/bytes/bool/
            Pointer.  Floats are out (-0.0 == 0.0 with different
            bytes, nan != nan with equal bytes); cross-dtype pairs
            are out (True == 1, 1 == 1.0 across columns)."""
            exact = {dt.INT, dt.STR, dt.BYTES, dt.BOOL, dt.POINTER}
            for le, re_ in zip(self._left_on, self._right_on):
                lcol = left_table.schema.__columns__.get(le.name)
                rcol = right_table.schema.__columns__.get(re_.name)
                if lcol is None or rcol is None:
                    return False
                ld = lcol.dtype.strip_optional()
                rd = rcol.dtype.strip_optional()
                if ld is not rd or ld not in exact:
                    return False
            return True

        mode = {"left": 1, "right": 2}.get(id_side, 0)
        outer = self._mode is not JoinMode.INNER
        if (
            vc.ENABLED
            and l_idxs
            and None not in l_idxs
            and None not in r_idxs
            and _hashable_key_dtypes()
            and not (outer and mode != 0)
        ):
            node.native_spec = (tuple(l_idxs), tuple(r_idxs), mode)
        if vc.ENABLED and l_idxs and None not in l_idxs and None not in r_idxs:
            # batched exchange routing: the route key is hash_values over
            # the raw join-key column values (none_guard semantics: a
            # None/Error key value routes the row by its own key), which
            # the native route kernel reproduces byte-for-byte — no
            # dtype gate needed, unlike the index fast path above
            node.exchange_route_cols = {
                0: (tuple(l_idxs), False),
                1: (tuple(r_idxs), False),
            }
        return node

    def select(self, *args, **kwargs) -> Table:
        exprs: dict[str, Any] = {}
        for a in args:
            if isinstance(a, ThisSlice):
                base = a._base
                if getattr(base, "_kind", None) == "left":
                    for n in a._column_names(self._left):
                        exprs[n] = ColumnReference(left_ph, n)
                elif getattr(base, "_kind", None) == "right":
                    for n in a._column_names(self._right):
                        exprs[n] = ColumnReference(right_ph, n)
                else:
                    all_names = self._all_names()
                    for n in (a._keep if a._keep is not None else all_names):
                        if n not in a._without:
                            exprs[n] = ColumnReference(this, n)
            elif isinstance(a, TableSlice):
                for n in a.column_names():
                    exprs[n] = ColumnReference(a._table, n)
            else:
                exprs[_name_of_expr(a)] = a
        exprs.update(kwargs)
        return self._select_impl(exprs)

    def _all_names(self) -> list[str]:
        names = list(self._left.column_names())
        for n in self._right.column_names():
            if n not in names:
                names.append(n)
        return names

    def _as_table(self) -> Table:
        exprs: dict[str, Any] = {}
        l_names = set(self._left.column_names())
        r_names = set(self._right.column_names())
        for n in self._left.column_names():
            exprs[n] = ColumnReference(left_ph, n)
        for n in self._right.column_names():
            if n in l_names:
                continue  # left wins on collision for the implicit projection
            exprs[n] = ColumnReference(right_ph, n)
        return self._select_impl(exprs)

    def filter(self, expression) -> Table:
        return self._as_table().filter(expression)

    def groupby(self, *args, **kwargs):
        return self._as_table().groupby(*args, **kwargs)

    def reduce(self, *args, **kwargs) -> Table:
        return self._as_table().reduce(*args, **kwargs)

    def _select_impl(self, exprs: Mapping[str, Any]) -> Table:
        left_table, right_table = self._left, self._right
        mode = self._mode

        class JoinBinder(Binder):
            def __init__(self, lowerer):
                self.lowerer = lowerer
                self.l_names = left_table.column_names()
                self.r_names = right_table.column_names()
                self.n_l = len(self.l_names)

            def _left_acc(self, name):
                if name == "id":
                    return lambda key, row: (
                        Pointer(row[0]) if row[0] is not None else None
                    )
                idx = self.l_names.index(name)
                return lambda key, row: (row[2][idx] if row[2] is not None else None)

            def _right_acc(self, name):
                if name == "id":
                    return lambda key, row: (
                        Pointer(row[1]) if row[1] is not None else None
                    )
                idx = self.r_names.index(name)
                return lambda key, row: (row[3][idx] if row[3] is not None else None)

            def resolve(self, ref):
                tbl, name = ref.table, ref.name
                if tbl is left_table or (
                    isinstance(tbl, ThisPlaceholder) and tbl._kind == "left"
                ):
                    return self._left_acc(name)
                if tbl is right_table or (
                    isinstance(tbl, ThisPlaceholder) and tbl._kind == "right"
                ):
                    return self._right_acc(name)
                if isinstance(tbl, ThisPlaceholder):  # pw.this — search both
                    if name == "id":
                        return lambda key, row: Pointer(key)
                    in_l = name in self.l_names
                    in_r = name in self.r_names
                    if in_l and in_r:
                        raise ValueError(
                            f"column {name!r} is ambiguous in join select; "
                            "use pw.left/pw.right"
                        )
                    if in_l:
                        return self._left_acc(name)
                    if in_r:
                        return self._right_acc(name)
                    raise KeyError(name)
                if isinstance(tbl, Table):
                    raise ValueError(
                        "references to third tables in join select are not supported; "
                        "join with that table instead"
                    )
                raise ValueError(f"cannot resolve {ref!r}")

            def resolve_dtype(self, ref):
                tbl, name = ref.table, ref.name
                opt_l = mode in (JoinMode.RIGHT, JoinMode.OUTER)
                opt_r = mode in (JoinMode.LEFT, JoinMode.OUTER)

                def maybe_opt(t, make_opt):
                    return dt.Optional(t) if make_opt else t

                if tbl is left_table or (
                    isinstance(tbl, ThisPlaceholder) and tbl._kind == "left"
                ):
                    if name == "id":
                        return maybe_opt(dt.POINTER, opt_l)
                    col = left_table.schema.__columns__.get(name)
                    return maybe_opt(col.dtype if col else dt.ANY, opt_l)
                if tbl is right_table or (
                    isinstance(tbl, ThisPlaceholder) and tbl._kind == "right"
                ):
                    if name == "id":
                        return maybe_opt(dt.POINTER, opt_r)
                    col = right_table.schema.__columns__.get(name)
                    return maybe_opt(col.dtype if col else dt.ANY, opt_r)
                if isinstance(tbl, ThisPlaceholder):
                    if name in left_table.schema.__columns__:
                        return maybe_opt(
                            left_table.schema.__columns__[name].dtype, opt_l
                        )
                    if name in right_table.schema.__columns__:
                        return maybe_opt(
                            right_table.schema.__columns__[name].dtype, opt_r
                        )
                return dt.ANY

        jr = self

        def _project_spec():
            """((src, idx), ...) when every output is a plain left/right
            column or id pick — the native join projection's contract
            (srcs: 0 lrow[idx], 1 rrow[idx], 2/3 left/right id, 4 out id).
            None when any expression needs the row interpreter."""
            l_names = left_table.column_names()
            r_names = right_table.column_names()
            spec = []
            for e in exprs.values():
                if not isinstance(e, ColumnReference):
                    return None
                tbl, name = e.table, e.name
                side = JoinResult._side_of(tbl, left_table, right_table)
                if side is None and isinstance(tbl, ThisPlaceholder):
                    if name == "id":
                        spec.append((4, -1))
                        continue
                    in_l, in_r = name in l_names, name in r_names
                    if in_l and in_r:
                        return None  # ambiguity error stays on the row path
                    side = "left" if in_l else ("right" if in_r else None)
                if side == "left":
                    spec.append((2, -1) if name == "id" else (0, l_names.index(name)))
                elif side == "right":
                    spec.append((3, -1) if name == "id" else (1, r_names.index(name)))
                else:
                    return None
            return tuple(spec)

        def _flat_select() -> "Table | None":
            """Computed join-selects as: native flat projection of every
            REFERENCED side column → a standard (vec-compilable) select
            over the flat table.  The join step and the column extraction
            stay native; only the arithmetic runs in the expression
            engine — which vectorizes it.  None = unsupported shape (the
            row path handles it, including its error surfaces)."""
            l_names = left_table.column_names()
            r_names = right_table.column_names()
            refs: list[ColumnReference] = []

            def walk(e):
                if isinstance(e, ColumnReference):
                    refs.append(e)
                    return
                for s in e._sub_expressions():
                    walk(s)

            for e in exprs.values():
                if not isinstance(e, expr_mod.ColumnExpression):
                    return None
                walk(e)

            needed: dict[str, tuple[int, int]] = {}  # name -> (src, idx)
            sides: dict[str, str] = {}
            for ref in refs:
                name = ref.name
                if name == "id":
                    return None  # id refs keep the row path
                side = JoinResult._side_of(ref.table, left_table, right_table)
                if side is None and isinstance(ref.table, ThisPlaceholder):
                    in_l, in_r = name in l_names, name in r_names
                    if in_l == in_r:
                        return None  # ambiguous / unknown: row path raises
                    side = "left" if in_l else "right"
                if side is None:
                    return None
                if sides.get(name, side) != side:
                    return None  # same name from both sides: would collide
                sides[name] = side
                if name not in needed:
                    src = 0 if side == "left" else 1
                    names_ = l_names if side == "left" else r_names
                    if name not in names_:
                        return None
                    needed[name] = (src, names_.index(name))
            if not needed:
                return None

            flat_names = list(needed)
            spec = tuple(needed[n] for n in flat_names)
            tmp = JoinBinder(None)
            cols = {}
            for n in flat_names:
                side_tbl = left_table if sides[n] == "left" else right_table
                try:
                    d = tmp.resolve_dtype(ColumnReference(side_tbl, n))
                except Exception:
                    d = dt.ANY
                cols[n] = schema_mod.ColumnSchema(name=n, dtype=d)

            def flat_build(lowerer: Lowerer) -> df.Node:
                join_node = jr._lower_join(lowerer)
                binder = JoinBinder(lowerer)
                accs = [
                    binder.resolve(
                        ColumnReference(
                            left_ph if sides[n] == "left" else right_ph, n
                        )
                    )
                    for n in flat_names
                ]

                def fn(key, row):
                    return tuple(a(key, row) for a in accs)

                node = df.ExprNode(lowerer.scope, join_node, fn)
                node.vec_join_project = spec
                return node

            flat_t = Table(
                schema_mod.schema_from_columns(cols), flat_build, universe=Universe()
            )
            mapping = {
                id(left_table): flat_t,
                id(right_table): flat_t,
                id(left_ph): flat_t,
                id(right_ph): flat_t,
                id(this): flat_t,
            }
            return flat_t.select(
                **{n: e._substitute(mapping) for n, e in exprs.items()}
            )

        def build(lowerer: Lowerer) -> df.Node:
            join_node = jr._lower_join(lowerer)
            binder = JoinBinder(lowerer)
            fns = [compile_expr(e, binder) for e in exprs.values()]

            def fn(key, row):
                return tuple(f(key, row) for f in fns)

            node = df.ExprNode(lowerer.scope, join_node, fn)
            node.vec_join_project = _project_spec()
            return node

        from pathway_tpu.internals import vector_compiler as _vc

        if _vc.ENABLED and _project_spec() is None:
            # only worthwhile with the vector compiler on (the flat graph
            # adds a node whose payoff is the columnar expression pass);
            # off also serves as the parity toggle for tests
            flat = _flat_select()
            if flat is not None:
                return flat

        tmp_binder = JoinBinder(None)
        cols = {}
        for n, e in exprs.items():
            e_w = expr_mod._wrap(e)
            try:
                d = e_w._infer_dtype(tmp_binder.resolve_dtype)
            except Exception:
                d = dt.ANY
            cols[n] = schema_mod.ColumnSchema(name=n, dtype=d)
        return Table(schema_mod.schema_from_columns(cols), build, universe=Universe())


# convenience top-level functions mirroring pw.join / pw.groupby
def join(left_t, right_t, *on, id=None, how=JoinMode.INNER, **kw):
    return left_t.join(right_t, *on, id=id, how=how)


def join_inner(left_t, right_t, *on, **kw):
    return left_t.join_inner(right_t, *on, **kw)


# Typed aliases for reference API parity (reference exports distinct
# GroupedJoinResult / OuterJoinResult classes from groupbys.py/joins.py;
# here joins of every mode share JoinResult and groupby-after-join goes
# through GroupedTable, so the names bind to those implementations).
GroupedJoinResult = GroupedTable
OuterJoinResult = JoinResult


def join_left(left_t, right_t, *on, **kw):
    return left_t.join_left(right_t, *on, **kw)


def join_right(left_t, right_t, *on, **kw):
    return left_t.join_right(right_t, *on, **kw)


def join_outer(left_t, right_t, *on, **kw):
    return left_t.join_outer(right_t, *on, **kw)


def groupby(table, *args, **kwargs):
    return table.groupby(*args, **kwargs)


TableLike = Table


# ---------------------------------------------------------------------------
# user-frame tracing on the public entry points (reference trace.py:123-131:
# the decorator is applied at each method there; applying it here in one
# sweep keeps the method bodies free of wrapper noise)
# ---------------------------------------------------------------------------

from pathway_tpu.internals.trace import trace_user_frame as _trace_user_frame  # noqa: E402

_TRACED_TABLE_METHODS = (
    "select", "with_columns", "without", "rename", "rename_columns",
    "rename_by_dict", "with_prefix", "with_suffix", "filter", "split",
    "flatten", "pointer_from", "with_id_from", "with_id", "concat",
    "concat_reindex", "update_rows", "update_cells", "intersect",
    "difference", "restrict", "having", "ix", "ix_ref", "groupby",
    "reduce", "deduplicate", "sort", "diff", "cast_to_types",
    "update_types", "join", "join_inner", "join_left", "join_right",
    "join_outer", "with_universe_of",
)

for _cls in (Table, GroupedTable, JoinResult, Joinable):
    for _name in _TRACED_TABLE_METHODS:
        _fn = _cls.__dict__.get(_name)
        if callable(_fn) and not isinstance(_fn, (property, staticmethod, classmethod)):
            setattr(_cls, _name, _trace_user_frame(_fn))
