"""``pw.universes`` — universe promises (parity: python/pathway/universes.py)."""

from __future__ import annotations

from pathway_tpu.internals.table import Table


def promise_are_equal(*tables: Table) -> None:
    for t in tables[1:]:
        tables[0].promise_universes_are_equal(t)


def promise_is_subset_of(subset: Table, superset: Table) -> None:
    subset.promise_universe_is_subset_of(superset)


def promise_are_pairwise_disjoint(*tables: Table) -> None:
    pass


__all__ = ["promise_are_equal", "promise_is_subset_of", "promise_are_pairwise_disjoint"]
