"""``expr.num.*`` numerical method namespace.

Parity target: ``/root/reference/python/pathway/internals/expressions/numerical.py``.
"""

from __future__ import annotations

import math

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import (
    ColumnExpression,
    MethodCallExpression,
)


class NumericalNamespace:
    r"""``col.num`` — numerical operations on column expressions.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('x\n-2.75\n3.5')
    >>> r = t.select(a=pw.this.x.num.abs(), rnd=pw.this.x.num.round(1))
    >>> pw.debug.compute_and_print(r, include_id=False)
    a    | rnd
    2.75 | -2.8
    3.5  | 3.5
    """
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def _m(self, name, fun, ret, *args, propagate_none=True):
        return MethodCallExpression(
            f"num.{name}", fun, ret, [self._expr, *args], propagate_none=propagate_none
        )

    def abs(self):
        return self._m("abs", abs, lambda ts: ts[0])

    def round(self, decimals=0):
        return self._m(
            "round",
            lambda v, d: round(v, d) if d else float(round(v)) if isinstance(v, float) else round(v),
            lambda ts: ts[0],
            decimals,
        )

    def fill_na(self, default_value):
        def impl(v, d):
            if v is None:
                return d
            if isinstance(v, float) and math.isnan(v):
                return d
            return v

        return self._m(
            "fill_na",
            impl,
            lambda ts: dt.unoptionalize(ts[0]),
            default_value,
            propagate_none=False,
        )
