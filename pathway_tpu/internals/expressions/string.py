"""``expr.str.*`` string method namespace.

Parity target: ``/root/reference/python/pathway/internals/expressions/string.py``.
"""

from __future__ import annotations

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression, MethodCallExpression


class StringNamespace:
    r"""``col.str`` — string operations on column expressions.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('w\nHello World\nbye')
    >>> r = t.select(
    ...     up=pw.this.w.str.upper(),
    ...     n=pw.this.w.str.len(),
    ...     first=pw.this.w.str.split(' ').get(0, default=''),
    ... )
    >>> pw.debug.compute_and_print(r, include_id=False)
    up          | n  | first
    BYE         | 3  | bye
    HELLO WORLD | 11 | Hello
    """
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def _m(self, name, fun, ret, *args):
        return MethodCallExpression(f"str.{name}", fun, ret, [self._expr, *args])

    def lower(self):
        return self._m("lower", str.lower, dt.STR)

    def upper(self):
        return self._m("upper", str.upper, dt.STR)

    def reversed(self):
        return self._m("reversed", lambda s: s[::-1], dt.STR)

    def strip(self, chars=None):
        return self._m("strip", lambda s, c: s.strip(c), dt.STR, chars)

    def title(self):
        return self._m("title", str.title, dt.STR)

    def swap_case(self):
        return self._m("swap_case", str.swapcase, dt.STR)

    def len(self):
        return self._m("len", len, dt.INT)

    def count(self, sub, start=None, end=None):
        return self._m(
            "count",
            lambda s, x, b, e: s.count(x, b, e if e is not None else len(s)),
            dt.INT,
            sub,
            start if start is not None else 0,
            end,
        )

    def find(self, sub, start=None, end=None):
        return self._m(
            "find",
            lambda s, x, b, e: s.find(x, b, e if e is not None else len(s)),
            dt.INT,
            sub,
            start if start is not None else 0,
            end,
        )

    def rfind(self, sub, start=None, end=None):
        return self._m(
            "rfind",
            lambda s, x, b, e: s.rfind(x, b, e if e is not None else len(s)),
            dt.INT,
            sub,
            start if start is not None else 0,
            end,
        )

    def startswith(self, prefix):
        return self._m("startswith", lambda s, p: s.startswith(p), dt.BOOL, prefix)

    def endswith(self, suffix):
        return self._m("endswith", lambda s, p: s.endswith(p), dt.BOOL, suffix)

    def removeprefix(self, prefix):
        return self._m("removeprefix", lambda s, p: s.removeprefix(p), dt.STR, prefix)

    def removesuffix(self, suffix):
        return self._m("removesuffix", lambda s, p: s.removesuffix(p), dt.STR, suffix)

    def replace(self, old_value, new_value, count=-1):
        return self._m(
            "replace", lambda s, o, n, c: s.replace(o, n, c), dt.STR, old_value, new_value, count
        )

    def split(self, delimiter=None):
        return self._m(
            "split", lambda s, d: tuple(s.split(d)), dt.List(dt.STR), delimiter
        )

    def slice(self, start, end):
        return self._m("slice", lambda s, b, e: s[b:e], dt.STR, start, end)

    def parse_int(self, optional: bool = False):
        def impl(s):
            try:
                return int(s)
            except (ValueError, TypeError):
                if optional:
                    return None
                raise

        return self._m("parse_int", impl, dt.Optional(dt.INT) if optional else dt.INT)

    def parse_float(self, optional: bool = False):
        def impl(s):
            try:
                return float(s)
            except (ValueError, TypeError):
                if optional:
                    return None
                raise

        return self._m("parse_float", impl, dt.Optional(dt.FLOAT) if optional else dt.FLOAT)

    def parse_bool(self, true_values=("on", "true", "yes", "1"), false_values=("off", "false", "no", "0"), optional: bool = False):
        def impl(s):
            low = s.lower()
            if low in true_values:
                return True
            if low in false_values:
                return False
            if optional:
                return None
            raise ValueError(f"cannot parse {s!r} as bool")

        return self._m("parse_bool", impl, dt.Optional(dt.BOOL) if optional else dt.BOOL)

    def to_datetime(self, fmt, contains_timezone: bool | None = None):
        import datetime as _dt

        ret = dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE
        return self._m("to_datetime", lambda s, f: _dt.datetime.strptime(s, f), ret, fmt)
