"""``expr.dt.*`` datetime method namespace.

Parity target: ``/root/reference/python/pathway/internals/expressions/date_time.py``.
DateTimeNaive is a tz-naive ``datetime.datetime``; DateTimeUtc is tz-aware;
Duration is ``datetime.timedelta`` — same user-visible model as the reference.
"""

from __future__ import annotations

import datetime
from zoneinfo import ZoneInfo

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression, MethodCallExpression

_UTC = datetime.timezone.utc


def _strptime_impl(s: str, fmt: str) -> datetime.datetime:
    return datetime.datetime.strptime(s, fmt)


def _round_dt(value: datetime.datetime, duration: datetime.timedelta) -> datetime.datetime:
    epoch = (
        datetime.datetime(1970, 1, 1, tzinfo=value.tzinfo)
        if value.tzinfo
        else datetime.datetime(1970, 1, 1)
    )
    total = (value - epoch).total_seconds()
    step = duration.total_seconds()
    rounded = round(total / step) * step
    return epoch + datetime.timedelta(seconds=rounded)


def _floor_dt(value: datetime.datetime, duration: datetime.timedelta) -> datetime.datetime:
    epoch = (
        datetime.datetime(1970, 1, 1, tzinfo=value.tzinfo)
        if value.tzinfo
        else datetime.datetime(1970, 1, 1)
    )
    total = (value - epoch).total_seconds()
    step = duration.total_seconds()
    floored = (total // step) * step
    return epoch + datetime.timedelta(seconds=floored)


def _as_duration(d) -> datetime.timedelta:
    if isinstance(d, datetime.timedelta):
        return d
    raise TypeError(f"expected Duration, got {type(d)}")


class DateTimeNamespace:
    r"""``col.dt`` — datetime operations on column expressions.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('ts\n2024-03-01T10:30:00')
    >>> r = t.select(
    ...     d=pw.this.ts.dt.strptime('%Y-%m-%dT%H:%M:%S').dt.strftime('%d.%m.%Y'),
    ... )
    >>> pw.debug.compute_and_print(r, include_id=False)
    d
    01.03.2024
    """
    def __init__(self, expr: ColumnExpression):
        self._expr = expr

    def _m(self, name, fun, ret, *args, **kwargs):
        return MethodCallExpression(f"dt.{name}", fun, ret, [self._expr, *args], kwargs)

    # field extraction
    def year(self):
        return self._m("year", lambda v: v.year, dt.INT)

    def month(self):
        return self._m("month", lambda v: v.month, dt.INT)

    def day(self):
        return self._m("day", lambda v: v.day, dt.INT)

    def hour(self):
        return self._m("hour", lambda v: v.hour, dt.INT)

    def minute(self):
        return self._m("minute", lambda v: v.minute, dt.INT)

    def second(self):
        return self._m("second", lambda v: v.second, dt.INT)

    def millisecond(self):
        return self._m("millisecond", lambda v: v.microsecond // 1000, dt.INT)

    def microsecond(self):
        return self._m("microsecond", lambda v: v.microsecond, dt.INT)

    def nanosecond(self):
        return self._m("nanosecond", lambda v: v.microsecond * 1000, dt.INT)

    def weekday(self):
        return self._m("weekday", lambda v: v.weekday(), dt.INT)

    # timestamps
    def timestamp(self, unit: str = "ns"):
        mult = {"ns": 1e9, "us": 1e6, "ms": 1e3, "s": 1.0}[unit]

        def impl(v):
            if v.tzinfo is None:
                base = v.replace(tzinfo=_UTC)
            else:
                base = v
            return base.timestamp() * mult

        return self._m("timestamp", impl, dt.FLOAT)

    def from_timestamp(self, unit: str = "s"):
        div = {"ns": 1e9, "us": 1e6, "ms": 1e3, "s": 1.0}[unit]
        return self._m(
            "from_timestamp",
            lambda v: datetime.datetime.utcfromtimestamp(v / div),
            dt.DATE_TIME_NAIVE,
        )

    def utc_from_timestamp(self, unit: str = "s"):
        div = {"ns": 1e9, "us": 1e6, "ms": 1e3, "s": 1.0}[unit]
        return self._m(
            "utc_from_timestamp",
            lambda v: datetime.datetime.fromtimestamp(v / div, tz=_UTC),
            dt.DATE_TIME_UTC,
        )

    # formatting / parsing
    def strftime(self, fmt):
        return self._m("strftime", lambda v, f: v.strftime(f), dt.STR, fmt)

    def strptime(self, fmt, contains_timezone: bool | None = None):
        ret = dt.DATE_TIME_UTC if contains_timezone else dt.DATE_TIME_NAIVE
        return self._m("strptime", _strptime_impl, ret, fmt)

    # tz conversions
    def to_utc(self, from_timezone: str):
        tz = ZoneInfo(from_timezone)
        return self._m(
            "to_utc",
            lambda v: v.replace(tzinfo=tz).astimezone(_UTC),
            dt.DATE_TIME_UTC,
        )

    def to_naive_in_timezone(self, timezone: str):
        tz = ZoneInfo(timezone)
        return self._m(
            "to_naive_in_timezone",
            lambda v: v.astimezone(tz).replace(tzinfo=None),
            dt.DATE_TIME_NAIVE,
        )

    # rounding
    def round(self, duration):
        return self._m(
            "round",
            lambda v, d: _round_dt(v, _as_duration(d)),
            lambda ts: ts[0],
            duration,
        )

    def floor(self, duration):
        return self._m(
            "floor",
            lambda v, d: _floor_dt(v, _as_duration(d)),
            lambda ts: ts[0],
            duration,
        )

    # duration decomposition
    def nanoseconds(self):
        return self._m("nanoseconds", lambda v: int(v.total_seconds() * 1e9), dt.INT)

    def microseconds(self):
        return self._m("microseconds", lambda v: int(v.total_seconds() * 1e6), dt.INT)

    def milliseconds(self):
        return self._m("milliseconds", lambda v: int(v.total_seconds() * 1e3), dt.INT)

    def seconds(self):
        return self._m("seconds", lambda v: int(v.total_seconds()), dt.INT)

    def minutes(self):
        return self._m("minutes", lambda v: int(v.total_seconds() // 60), dt.INT)

    def hours(self):
        return self._m("hours", lambda v: int(v.total_seconds() // 3600), dt.INT)

    def days(self):
        return self._m("days", lambda v: v.days, dt.INT)

    def weeks(self):
        return self._m("weeks", lambda v: v.days // 7, dt.INT)
