"""User-frame error re-tracing.

Parity target: ``/root/reference/python/pathway/internals/trace.py:92-140``
— when a public API call or a run-time engine step fails, the exception
gains a note pointing at the USER'S file:line (the last stack frame
outside the framework), instead of leaving them to dig through framework
frames.

Two hooks:

* :func:`trace_user_frame` decorates public Table/expression entry points
  (build-time errors: bad column names, type mismatches);
* :meth:`Trace.from_traceback` is captured when a Table recipe is created
  and replayed by the runner when an operator lowered from that table
  fails mid-run (run-time errors fire far from the user's code).
"""

from __future__ import annotations

import functools
import traceback
from dataclasses import dataclass
from typing import Any, Callable, TypeVar

import os as _os

# everything under the installed package is framework code — excluding by
# package root (not an enumerated subpackage list) means a frame inside
# e.g. pathway_tpu/demo or pathway_tpu/ops can never masquerade as user
# code when a new subpackage is added
_PKG_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))) + _os.sep


@dataclass(frozen=True)
class Frame:
    filename: str
    line_number: int | None
    line: str | None
    function: str

    def is_external(self) -> bool:
        if "/tests/test_" in self.filename:
            return True
        return not self.filename.startswith(_PKG_ROOT) and "@beartype" not in self.filename

    def is_marker(self) -> bool:
        return self.function == "_pathway_trace_marker"


@dataclass(frozen=True)
class Trace:
    frames: list[Frame]
    user_frame: Frame | None

    @staticmethod
    def from_traceback() -> "Trace":
        frames = [
            Frame(
                filename=e.filename,
                line_number=e.lineno,
                line=e.line,
                function=e.name,
            )
            for e in traceback.extract_stack()[:-1]
        ]
        user_frame: Frame | None = None
        for frame in frames:
            if frame.is_marker():
                break
            if frame.is_external():
                user_frame = frame
        return Trace(frames=frames, user_frame=user_frame)


def user_frame_from_stack() -> Frame | None:
    """The innermost user frame of the CURRENT stack.

    Called on every Table construction, so it must be cheap: a raw
    ``sys._getframe`` walk that stops at the first external frame and
    reads exactly one source line — not ``traceback.extract_stack``,
    which builds FrameSummaries (with source reads) for the whole stack.
    """
    import linecache
    import sys

    f = sys._getframe(1)
    while f is not None:
        filename = f.f_code.co_filename
        if Frame(filename, None, None, f.f_code.co_name).is_external():
            line = linecache.getline(filename, f.f_lineno).strip()
            return Frame(filename, f.f_lineno, line or None, f.f_code.co_name)
        f = f.f_back
    return None


def _format_frame(frame: Frame) -> str:
    return (
        "Occurred here:\n"
        f"    Line: {frame.line}\n"
        f"    File: {frame.filename}:{frame.line_number}"
    )


def add_trace_note(e: BaseException, frame: Frame) -> None:
    if getattr(e, "_pathway_trace_note", None) is not None:
        return  # first (innermost) note wins, like the reference
    note = _format_frame(frame)
    e._pathway_trace_note = note  # type: ignore[attr-defined]
    if hasattr(e, "add_note"):  # BaseException.add_note is 3.11+
        e.add_note(note)
    else:  # 3.10: emulate PEP 678 so tooling reading __notes__ still works
        notes = getattr(e, "__notes__", None)
        if notes is None:
            notes = []
            e.__notes__ = notes  # type: ignore[attr-defined]
        notes.append(note)


def _reraise_with_user_frame(e: Exception) -> None:
    tb = e.__traceback__
    if tb is not None:
        tb = tb.tb_next  # drop the marker wrapper frame
    e = e.with_traceback(tb)
    if getattr(e, "_pathway_trace_note", None) is not None:
        raise e
    user_frame = Trace.from_traceback().user_frame
    if user_frame is not None:
        add_trace_note(e, user_frame)
    raise e


F = TypeVar("F", bound=Callable[..., Any])


def trace_user_frame(func: F) -> F:
    """Decorate a public entry point: exceptions gain the user's
    file:line as an exception note (reference trace.py:123-131)."""

    @functools.wraps(func)
    def _pathway_trace_marker(*args, **kwargs):
        try:
            return func(*args, **kwargs)
        except Exception as e:
            _reraise_with_user_frame(e)

    return _pathway_trace_marker  # type: ignore[return-value]
