"""``pathway_tpu top`` — a live data-plane view over ``GET /status``.

The htop of a running pipeline: polls the monitoring HTTP server
(``engine/http_server.py``, enabled with ``pw.run(with_http_server=True)``
or ``PATHWAY_MONITORING_HTTP_PORT``) and renders, per refresh:

* header — run id, epochs processed, **epoch rate** (derived from the
  delta between polls), epoch-duration p50/p95/p99;
* freshness — per-output staleness and end-to-end ingest→delivery
  latency quantiles (``engine/freshness.py``);
* backlog — every ``backlog.*`` wait point, ranked worst-first, so the
  bottleneck stage reads off the top line;
* device — the DeviceExecutor panel (``pathway_tpu/device/``): dispatch
  rate, queue depth/age, compile-cache cold/warm discipline, padding
  waste, roofline utilization and HBM use, plus the fault-tolerance
  state (tripped circuit breakers, OOM bucket caps, host-fallback /
  quarantine / dispatch-restart counts);
* serving — the REST admission panel (``engine/serving.py``): in-flight
  occupancy, queue depth, per-code request counts, latency quantiles,
  shed/deadline counters, and the degraded/draining flags;
* requests — request-trace volume and ring extremes
  (``engine/tracing.py``; full waterfalls via ``pathway_tpu requests``);
* slo — every declared objective (``engine/slo.py``) with its remaining
  error budget and multi-window burn rates;
* operators — the per-operator progress table of the ``/status`` body.

Pure functions (`render_top`) are separated from I/O (`fetch_status`) so
tests pin the render without a server and the CLI stays a thin loop.
"""

from __future__ import annotations

import json
from typing import Any

from pathway_tpu.engine.metrics import split_labeled_name


class StatusUnavailable(RuntimeError):
    """The monitoring endpoint could not be reached or parsed — rendered
    by the CLI as a clear non-zero exit, never a traceback."""


def fetch_status(url: str, timeout: float = 2.0) -> dict[str, Any]:
    """One ``GET /status`` poll; raises :class:`StatusUnavailable` with an
    actionable message on any failure (server down, wrong port, bad
    body)."""
    import http.client
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            body = r.read().decode()
    except (
        urllib.error.URLError,
        # a non-HTTP listener on the polled port (the comm mesh port is
        # an easy mix-up) raises BadStatusLine — an HTTPException, not a
        # URLError — and must get the same clean exit
        http.client.HTTPException,
        OSError,
        TimeoutError,
    ) as exc:
        raise StatusUnavailable(
            f"cannot reach {url} ({exc}) — is the pipeline running with "
            "with_http_server=True (or PATHWAY_MONITORING_HTTP_PORT set)?"
        ) from exc
    try:
        payload = json.loads(body)
    except ValueError as exc:
        raise StatusUnavailable(
            f"{url} returned a non-JSON body ({exc}) — not a pathway_tpu "
            "monitoring endpoint?"
        ) from exc
    if not isinstance(payload, dict):
        raise StatusUnavailable(f"{url} returned non-object JSON")
    return payload


def _labeled(section: dict[str, float], base: str) -> dict[str, float]:
    """``{label-value: value}`` for every ``base{...}`` key of a scalar
    section, keyed by the first label's value (output=, source=, peer=)."""
    out: dict[str, float] = {}
    for key, value in (section or {}).items():
        name, labels = split_labeled_name(key)
        if name != base:
            continue
        label = next(iter(labels.values()), "") if labels else ""
        out[label] = value
    return out


def render_waterfall(trace: dict[str, Any], width: int = 32) -> str:
    """One finished request trace as a span waterfall: each span's
    offset/duration plus a proportional bar against the request's whole
    duration — a slow request decomposes visually into queue wait vs
    coalesce vs device dispatch vs generation ticks."""
    trace_id = trace.get("trace_id") or "?"
    duration_s = trace.get("duration_s") or 0.0
    status = trace.get("status")
    header = (
        f"trace {trace_id} [{trace.get('route') or '-'}]"
        f"{'' if status is None else f' {status}'}"
        f" · {duration_s * 1000:.1f} ms · {len(trace.get('spans') or [])} "
        "span(s)"
    )
    dropped = trace.get("spans_dropped") or 0
    if dropped:
        header += f" (+{dropped} dropped)"
    lines = [header]
    start0 = trace.get("start") or 0.0
    total = max(duration_s, 1e-9)
    spans = sorted(
        trace.get("spans") or [], key=lambda s: (s.get("start") or 0.0)
    )
    for span in spans:
        offset = max(0.0, (span.get("start") or 0.0) - start0)
        dur = span.get("duration_s") or 0.0
        pre = min(width - 1, int(offset / total * width))
        bar_len = max(1, min(width - pre, int(round(dur / total * width))))
        bar = "·" * pre + "█" * bar_len
        attrs = span.get("attributes") or {}
        attr_str = " ".join(f"{k}={v}" for k, v in attrs.items())
        lines.append(
            f"  {span.get('name', '?'):<24} {offset * 1000:>8.1f}ms "
            f"+{dur * 1000:>8.1f}ms  |{bar:<{width}}|"
            + (f"  {attr_str}" if attr_str else "")
        )
    return "\n".join(lines)


def render_requests(
    traces: list[dict[str, Any]], limit: int = 10, width: int = 32
) -> str:
    """The ``pathway_tpu requests`` body: up to ``limit`` waterfalls."""
    if not traces:
        return (
            "no finished request traces buffered — is the serving path "
            "live (and PATHWAY_TRACE_REQUESTS not 0)?"
        )
    return "\n\n".join(
        render_waterfall(t, width=width) for t in traces[:limit]
    )


def render_top(
    status: dict[str, Any],
    prev: dict[str, Any] | None = None,
    interval_s: float | None = None,
) -> str:
    """One frame of the live view from a ``/status`` payload (tolerates
    partial payloads from older servers — sections simply drop out)."""
    lines: list[str] = []
    epochs = status.get("epochs") or 0
    header = f"pathway_tpu top · run {status.get('run_id') or '-'} · epochs {epochs}"
    if prev is not None and interval_s:
        rate = max(0, epochs - (prev.get("epochs") or 0)) / interval_s
        header += f" · {rate:.1f} epochs/s"
    epoch_q = status.get("epoch") or {}
    quantiles = [
        f"{suffix[-3:]} {epoch_q[key]:.2f} ms"
        for suffix in ("p50", "p95", "p99")
        for key in (f"epoch.duration.ms.{suffix}",)
        if key in epoch_q
    ]
    if quantiles:
        header += " · epoch " + " / ".join(quantiles)
    lines.append(header)

    freshness = status.get("freshness") or {}
    staleness = _labeled(freshness, "output.staleness.s")
    if staleness:
        lines.append("")
        lines.append("freshness (per output)")
        e2e = {
            q: _labeled(freshness, f"freshness.e2e.ms.{q}")
            for q in ("p50", "p95", "p99")
        }
        for label in sorted(staleness, key=lambda k: -staleness[k]):
            row = f"  {label:<24} staleness {staleness[label]:>8.2f} s"
            qs = [
                f"{q} {e2e[q][label]:.1f} ms"
                for q in ("p50", "p95", "p99")
                if label in e2e[q]
            ]
            if qs:
                row += "   e2e " + " / ".join(qs)
            lines.append(row)
        mesh = freshness.get("freshness.mesh.staleness.s")
        if mesh is not None:
            lines.append(f"  mesh worst staleness: {mesh:.2f} s")

    backlog = status.get("backlog") or {}
    ranked = sorted(backlog.items(), key=lambda kv: -kv[1])
    nonzero = [(k, v) for k, v in ranked if v]
    if backlog:
        lines.append("")
        lines.append("backlog (worst first)")
        if not nonzero:
            lines.append("  (all queues empty)")
        for key, value in nonzero:
            base, labels = split_labeled_name(key)
            label_str = (
                " [" + ",".join(f"{k}={v}" for k, v in labels.items()) + "]"
                if labels
                else ""
            )
            lines.append(f"  {base + label_str:<44} {value:>12g}")

    device = status.get("device") or {}
    if device:
        lines.append("")
        lines.append("device")
        batches = device.get("device.dispatch.batches") or 0.0
        row = f"  dispatch {int(batches)} batch(es)"
        if prev is not None and interval_s:
            prev_batches = (prev.get("device") or {}).get(
                "device.dispatch.batches"
            ) or 0.0
            row += f" ({max(0.0, batches - prev_batches) / interval_s:.1f}/s)"
        rows = device.get("device.dispatch.rows")
        if rows is not None:
            row += f" · {int(rows)} row(s)"
        p95 = device.get("device.dispatch.ms.p95")
        if p95 is not None:
            row += f" · dispatch p95 {p95:.2f} ms"
        lines.append(row)
        backlog_all = status.get("backlog") or {}
        queue = backlog_all.get("backlog.device.queue")
        if queue is not None:
            lines.append(
                f"  queue {int(queue)} job(s) · "
                f"{backlog_all.get('backlog.device.bytes', 0.0):.0f} B in "
                "flight · oldest "
                f"{backlog_all.get('backlog.device.age.s', 0.0):.2f} s"
            )
        cold = device.get("device.cache.cold")
        warmed = device.get("device.warmup.compiles")
        if cold is not None or warmed is not None:
            # after a full warmup, nonzero cold is a discipline bug — the
            # panel puts it next to the jit accounting that pins it
            cache = f"  cache: cold {int(cold or 0)} / warmed {int(warmed or 0)}"
            misses = device.get("jax.cache.miss")
            if misses is not None:
                cache += (
                    f" · jit {int(device.get('jax.compile.count') or 0)} "
                    f"compile(s) / {int(misses)} cache miss(es)"
                )
            lines.append(cache)
        waste = device.get("device.padding.waste.fraction")
        if waste is not None:
            lines.append(
                f"  padding waste {waste:.1%} "
                f"({int(device.get('device.padding.waste.rows') or 0)} pad "
                "row(s)) — replay with `pathway_tpu buckets`"
            )
        util = device.get("device.utilization")
        if util is not None:
            from pathway_tpu.device.telemetry import format_utilization

            lines.append(
                f"  utilization {format_utilization(util)} of "
                f"{device.get('device.peak.flops_per_s') or 0.0:.3g} FLOP/s "
                f"peak · achieved "
                f"{device.get('device.achieved.flops_per_s') or 0.0:.3g} "
                "FLOP/s"
            )
        hbm = device.get("device.hbm.bytes_in_use")
        if hbm is not None:
            lines.append(
                f"  hbm {hbm / (1 << 20):.1f} MiB in use · peak "
                f"{(device.get('device.hbm.peak') or 0.0) / (1 << 20):.1f} MiB"
            )
        # fault-tolerance panel (device/resilience.py): per-callable
        # breaker state plus the degraded-mode counters — a tripped
        # breaker or a quarantined batch must be visible at a glance
        breakers = _labeled(device, "device.breaker.state")
        tripped = {
            name: value for name, value in breakers.items() if value
        }
        if tripped:
            states = ", ".join(
                f"{name} {'OPEN' if value >= 1.0 else 'half-open'}"
                for name, value in sorted(tripped.items())
            )
            lines.append(f"  breaker: {states}")
        caps = _labeled(device, "device.bucket.cap")
        if caps:
            lines.append(
                "  oom ratchet: "
                + ", ".join(
                    f"{name} capped at bucket {int(cap)}"
                    for name, cap in sorted(caps.items())
                )
                + f" ({int(device.get('device.oom.splits') or 0)} split(s))"
            )
        fallback = device.get("device.fallback.batches")
        quarantined = device.get("device.quarantine.batches")
        restarts = device.get("device.dispatch.restarts")
        if fallback or quarantined or restarts:
            lines.append(
                f"  degraded: {int(fallback or 0)} host-fallback batch(es) "
                f"· {int(quarantined or 0)} quarantined "
                f"· {int(restarts or 0)} dispatch restart(s)"
            )

    columnar = status.get("columnar") or {}
    bail_total = sum(
        v for k, v in columnar.items() if k.startswith("columnar.bail.count")
    )
    if bail_total:
        # silent columnar→row fall-backs: the pipeline is paying row-wise
        # cost on operators its benchmarks ran columnar (docs/columnar.md)
        top_bails = sorted(
            (
                (k, v)
                for k, v in columnar.items()
                if k.startswith("columnar.bail.count") and v
            ),
            key=lambda kv: -kv[1],
        )[:3]
        detail = ", ".join(
            "{}={:g}".format(
                ",".join(
                    f"{lk}:{lv}"
                    for lk, lv in split_labeled_name(k)[1].items()
                )
                or "total",
                v,
            )
            for k, v in top_bails
        )
        lines.append("")
        lines.append(f"columnar: {int(bail_total)} bail(s) — {detail}")

    autoscaler = status.get("autoscaler") or {}
    if autoscaler.get("autoscaler.target.workers"):
        # the supervisor's scale-controller panel (lease/autoscaler.json
        # via the worker's registry collector): target topology, budget,
        # cooldown, and whether a live handoff is in flight right now
        phase = {
            0.0: "steady",
            1.0: "hot (dwell running)",
            2.0: "cooling down",
            3.0: "HANDOFF IN FLIGHT",
        }.get(autoscaler.get("autoscaler.phase") or 0.0, "steady")
        lines.append("")
        lines.append(
            f"autoscaler: target {int(autoscaler['autoscaler.target.workers'])} "
            f"worker(s) · {phase} · budget left "
            f"{int(autoscaler.get('autoscaler.budget.left') or 0)}"
        )
        cooldown = autoscaler.get("autoscaler.cooldown.remaining.s") or 0.0
        decisions = autoscaler.get("autoscaler.decisions.logged") or 0.0
        detail = f"  {int(decisions)} decision(s) logged"
        last = _labeled(autoscaler, "autoscaler.last.decision")
        for action, target in sorted(last.items()):
            detail += f" · last: {action} → {int(target)}"
        if cooldown > 0:
            detail += f" · cooldown {cooldown:.1f} s remaining"
        lines.append(detail)

    standby = status.get("standby") or {}
    if standby.get("standby.pool") or standby.get("supervisor.promotions"):
        # the warm-standby panel (engine/standby.py collector): pool
        # size, per-standby apply lag, and how many worker deaths were
        # absorbed by promotion instead of a group restart
        lines.append("")
        promotions = standby.get("supervisor.promotions") or 0.0
        row = (
            f"standby: pool {int(standby.get('standby.pool') or 0)} · "
            f"{int(promotions)} promotion(s)"
        )
        last_worker = standby.get("supervisor.promotions.last.worker")
        if promotions and last_worker is not None:
            row += f" (last adopted worker {int(last_worker)})"
        lines.append(row)
        lags = _labeled(standby, "standby.lag.s")
        chunks = _labeled(standby, "standby.verified.chunks")
        for sid in sorted(lags):
            detail = f"  standby {sid}: apply lag {lags[sid]:.2f} s"
            if sid in chunks:
                detail += f" · {int(chunks[sid])} chunk(s) verified"
            lines.append(detail)

    serving = status.get("serving") or {}
    if serving:
        # the admission-controller panel (engine/serving.py): occupancy
        # and the shed story — a 429 storm or an engaged shedder must be
        # visible at a glance, next to the pressure that caused it
        lines.append("")
        inflight = serving.get("serve.inflight") or 0.0
        inflight_b = serving.get("serve.inflight.bytes") or 0.0
        depth = serving.get("serve.queue.depth") or 0.0
        row = (
            f"serving: {int(inflight)} in flight "
            f"({inflight_b / (1 << 20):.2f} MiB) · queue {int(depth)}"
        )
        if serving.get("serve.draining"):
            row += " · DRAINING"
        elif serving.get("serve.degraded"):
            row += " · DEGRADED (shedding)"
        lines.append(row)
        by_code: dict[str, float] = {}
        sheds: dict[str, float] = {}
        lapsed: dict[str, float] = {}
        lats: dict[str, dict[str, float]] = {}
        for key, value in serving.items():
            name, labels = split_labeled_name(key)
            if name == "serve.requests":
                code = labels.get("code", "?")
                by_code[code] = by_code.get(code, 0.0) + value
            elif name == "serve.shed" and value:
                sheds[labels.get("reason", "?")] = value
            elif name == "serve.deadline.exceeded" and value:
                lapsed[labels.get("where", "?")] = value
            else:
                for q in ("p50", "p95", "p99"):
                    if name == f"serve.latency.ms.{q}":
                        route = labels.get("route", "")
                        lats.setdefault(route, {})[q] = value
        if by_code:
            lines.append(
                "  requests: "
                + " · ".join(
                    f"{code}×{int(v)}" for code, v in sorted(by_code.items())
                )
            )
        for route in sorted(lats):
            qs = " / ".join(
                f"{q} {lats[route][q]:.1f} ms"
                for q in ("p50", "p95", "p99")
                if q in lats[route]
            )
            lines.append(f"  latency [{route or '-'}]: {qs}")
        quarantined = serving.get("serve.quarantined")
        if sheds or lapsed or quarantined:
            parts = []
            if sheds:
                parts.append(
                    "shed "
                    + ", ".join(
                        f"{r}×{int(v)}" for r, v in sorted(sheds.items())
                    )
                )
            if lapsed:
                parts.append(
                    "deadline "
                    + ", ".join(
                        f"{w}×{int(v)}" for w, v in sorted(lapsed.items())
                    )
                )
            if quarantined:
                parts.append(f"quarantined {int(quarantined)}")
            lines.append("  " + " · ".join(parts))

    generation = status.get("generation") or {}
    if generation.get("generate.slots.total"):
        # the continuous-batching panel (serving/generation.py): slot and
        # page-pool occupancy tell at a glance whether the generation
        # loop is compute-bound (slots full, pages free) or memory-bound
        # (pages full, queue growing)
        lines.append("")
        active = generation.get("generate.slots.active") or 0.0
        total = generation.get("generate.slots.total") or 0.0
        depth = generation.get("generate.queue.depth") or 0.0
        pages_used = generation.get("generate.pages.used") or 0.0
        pages_total = generation.get("generate.pages.total") or 0.0
        rate = generation.get("generate.tokens_per_s") or 0.0
        lines.append(
            f"generation: {int(active)}/{int(total)} slot(s) · queue "
            f"{int(depth)} · pages {int(pages_used)}/{int(pages_total)} "
            f"· {rate:.1f} tok/s"
        )
        live = generation.get("generate.kv.bytes.live") or 0.0
        peak = generation.get("generate.kv.bytes.peak") or 0.0
        dense = generation.get("generate.kv.bytes.dense") or 0.0
        if dense:
            lines.append(
                f"  kv: {live / (1 << 20):.2f} MiB live · peak "
                f"{peak / (1 << 20):.2f} MiB · dense layout would hold "
                f"{dense / (1 << 20):.2f} MiB"
            )
        ttft: dict[str, float] = {}
        for key, value in generation.items():
            name, _labels = split_labeled_name(key)
            for q in ("p50", "p95", "p99"):
                if name == f"generate.ttft.ms.{q}":
                    ttft[q] = value
        if ttft:
            qs = " / ".join(
                f"{q} {ttft[q]:.1f} ms"
                for q in ("p50", "p95", "p99")
                if q in ttft
            )
            lines.append(f"  ttft: {qs}")
        churn = generation.get("generate.churn.synthetic")
        if churn:
            lines.append(f"  churn: {int(churn)} synthetic burst request(s)")

    requests = status.get("requests") or {}
    req_scalars = requests.get("scalars") or {}
    if req_scalars.get("trace.requests"):
        # the request-tracing line (engine/tracing.py): trace volume plus
        # the buffered ring's extremes — `pathway_tpu requests` renders
        # the full waterfalls
        lines.append("")
        row = (
            f"requests: {int(req_scalars['trace.requests'])} traced · "
            f"{int(req_scalars.get('trace.spans') or 0)} span(s) · "
            f"{int(req_scalars.get('trace.requests.buffered') or 0)} buffered"
        )
        slowest = req_scalars.get("trace.requests.slowest.ms")
        if slowest is not None:
            row += f" · slowest {slowest:.1f} ms"
        lines.append(row)

    slo = status.get("slo") or {}
    slos = slo.get("slos") or []
    if slos:
        # the SLO panel (engine/slo.py): every declared objective with
        # its budget + burn — a violating SLO must read off one line
        lines.append("")
        lines.append("slo (budget remaining · burn by window)")
        for entry in slos:
            burns = entry.get("burn") or {}
            burn_str = " / ".join(
                f"{window} ×{burns[window]:.2f}" for window in sorted(burns)
            )
            row = (
                f"  {entry.get('name', '?'):<16} "
                f"[{entry.get('objective', '')}]  budget "
                f"{entry.get('budget_remaining', 1.0):>6.1%}"
            )
            if burn_str:
                row += f" · burn {burn_str}"
            if entry.get("violating"):
                row += " · VIOLATING"
            lines.append(row)

    operators = status.get("operators") or {}
    if operators:
        lines.append("")
        lines.append(
            f"  {'operator':<20} {'rows in':>10} {'rows out':>10} "
            f"{'step ms':>9} {'lag ms':>8}"
        )
        rows = sorted(
            operators.items(),
            key=lambda kv: -(kv[1].get("step_ms") or 0.0),
        )
        for op_id, op in rows:
            name = f"{op.get('name', 'op')}#{op_id}"
            lag = op.get("lag_ms")
            lines.append(
                f"  {name:<20} {op.get('rows_in', 0):>10} "
                f"{op.get('rows_out', 0):>10} "
                f"{op.get('step_ms') or 0.0:>9.1f} "
                f"{'-' if lag is None else format(lag, '.0f'):>8}"
                + ("  [done]" if op.get("done") else "")
            )
    return "\n".join(lines)
