"""xpacks: llm toolkit and enterprise connectors."""
from pathway_tpu.xpacks import connectors, llm  # noqa: F401,E402
