"""VectorStoreServer / VectorStoreClient (parity: xpacks/llm/vector_store.py:39-769).

The legacy (pre-DocumentStore) vector index server: documents in, embedder +
splitter, REST endpoints /v1/retrieve, /v1/statistics, /v1/inputs.  Built on
DocumentStore + the brute-force device index; ``from_langchain_components``
and ``from_llamaindex_components`` adapt third-party splitters/embedders
when those packages are installed.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

import pathway_tpu as pw
from pathway_tpu.engine.types import Json
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.udfs import UDF, async_executor
from pathway_tpu.stdlib.indexing.retrievers import BruteForceKnnFactory
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.servers import DocumentStoreServer


def _as_embedder_udf(embedder: Any) -> UDF:
    """Accept a pw UDF, a plain callable, or an async callable."""
    if isinstance(embedder, UDF):
        return embedder
    if callable(embedder):
        import asyncio

        if asyncio.iscoroutinefunction(embedder):
            u = UDF(executor=async_executor())
            u.__wrapped__ = embedder
            return u
        u = UDF()

        def wrapped(text: str) -> np.ndarray:
            return np.asarray(embedder(text))

        u.__wrapped__ = wrapped
        return u
    raise TypeError(f"cannot use {type(embedder)} as an embedder")


class VectorStoreServer:
    """Index documents and serve retrieval queries (parity :39)."""

    def __init__(
        self,
        *docs: Table,
        embedder: Any = None,
        parser: Callable | None = None,
        splitter: Callable | None = None,
        doc_post_processors: list | None = None,
    ):
        if embedder is None:
            from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

            embedder = SentenceTransformerEmbedder()
        embedder = _as_embedder_udf(embedder)
        retriever_factory = BruteForceKnnFactory(embedder=embedder)
        self.document_store = self._document_store_cls(
            list(docs),
            retriever_factory,
            parser=parser,
            splitter=splitter,
            doc_post_processors=doc_post_processors,
        )
        self._server: DocumentStoreServer | None = None

    _document_store_cls: type[DocumentStore] = DocumentStore

    # constructor adapters (parity :~200)
    @classmethod
    def from_langchain_components(
        cls, *docs, embedder=None, parser=None, splitter=None, **kwargs
    ) -> "VectorStoreServer":
        sp = None
        if splitter is not None:

            def lc_splitter(text, metadata=None):
                return tuple((c, Json({})) for c in splitter.split_text(text))

            sp = UDF()
            sp.__wrapped__ = lc_splitter

        embed = None
        if embedder is not None:

            async def embed(text: str) -> np.ndarray:  # noqa: F811
                return np.asarray(await embedder.aembed_query(text))

        return cls(*docs, embedder=embed, parser=parser, splitter=sp, **kwargs)

    @classmethod
    def from_llamaindex_components(
        cls, *docs, transformations: list | None = None, parser=None, **kwargs
    ) -> "VectorStoreServer":
        embedder = None
        splitter = None
        for t in transformations or []:
            if hasattr(t, "get_text_embedding"):
                emb = t

                def embedder(text: str) -> np.ndarray:  # noqa: F811
                    return np.asarray(emb.get_text_embedding(text))

            elif hasattr(t, "split_text") or hasattr(t, "get_nodes_from_documents"):
                # llamaindex node parsers (SentenceSplitter etc.)
                node_parser = t
                sp = UDF()
                if hasattr(node_parser, "split_text"):
                    sp.__wrapped__ = lambda text: [
                        (c, Json({})) for c in node_parser.split_text(text)
                    ]
                else:
                    def _split_nodes(text, _np=node_parser):
                        from llama_index.core.schema import Document  # type: ignore

                        nodes = _np.get_nodes_from_documents([Document(text=text)])
                        return [(n.get_content(), Json({})) for n in nodes]

                    sp.__wrapped__ = _split_nodes
                splitter = sp
        if embedder is None:
            raise ValueError(
                "from_llamaindex_components: no embedding transformation found "
                "(expected one with .get_text_embedding); pass an embed_model "
                "in `transformations` — refusing to silently substitute the "
                "default embedder"
            )
        return cls(*docs, embedder=embedder, parser=parser, splitter=splitter, **kwargs)

    # query handlers (same signatures as the reference)
    def retrieve_query(self, retrieval_queries: Table) -> Table:
        return self.document_store.retrieve_query(retrieval_queries)

    def statistics_query(self, info_queries: Table) -> Table:
        return self.document_store.statistics_query(info_queries)

    def inputs_query(self, input_queries: Table) -> Table:
        return self.document_store.inputs_query(input_queries)

    @property
    def index(self):
        return self.document_store.index

    RetrieveQuerySchema = DocumentStore.RetrieveQuerySchema
    StatisticsQuerySchema = DocumentStore.StatisticsQuerySchema
    InputsQuerySchema = DocumentStore.InputsQuerySchema

    def run_server(
        self,
        host: str,
        port: int,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
    ):
        """Start the REST server + pipeline (parity :~600)."""
        # serve self (not the store) so subclass query overrides — e.g.
        # SlidesVectorStoreServer.inputs_query — reach the HTTP endpoints
        self._server = DocumentStoreServer(host, port, self)
        return self._server.run_server(
            threaded=threaded,
            with_cache=with_cache,
            cache_backend=cache_backend,
            terminate_on_error=terminate_on_error,
        )


class SlidesVectorStoreServer(VectorStoreServer):
    """Vector index server for the slide-search application
    (parity: vector_store.py:588-648).

    Uses the slide document store (default parser = ``SlideParser``) and
    answers ``/v1/inputs`` with the per-slide metadata captured *after*
    parsing and post-processing, with the bulky ``b64_image`` entries
    stripped — the reference's modified ``pw_list_documents`` behavior.
    """

    excluded_response_metadata = ["b64_image"]

    @property
    def _document_store_cls(self):
        from pathway_tpu.xpacks.llm.document_store import SlidesDocumentStore

        return SlidesDocumentStore

    def __init__(self, *docs, **kwargs):
        super().__init__(*docs, **kwargs)
        # the store's pack() reads its own attribute; propagate so
        # subclass-level excluded_response_metadata config takes effect
        self.document_store.excluded_response_metadata = self.excluded_response_metadata

    def inputs_query(self, input_queries: Table) -> Table:
        return self.document_store.parsed_documents_query(input_queries)

    def parsed_documents_query(self, parse_docs_queries: Table) -> Table:
        return self.document_store.parsed_documents_query(parse_docs_queries)


class VectorStoreClient:
    """HTTP client for a VectorStoreServer (parity :~700)."""

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: int = 15,
        additional_headers: dict | None = None,
    ):
        self.url = url or f"http://{host}:{port}"
        self.timeout = timeout
        self.headers = {"Content-Type": "application/json", **(additional_headers or {})}

    def _post(self, route: str, payload: dict) -> Any:
        from pathway_tpu.xpacks.llm._utils import send_post_request

        return send_post_request(
            self.url + route, payload, self.headers, self.timeout
        )

    def query(
        self, query: str, k: int = 3, metadata_filter: str | None = None, filepath_globpattern: str | None = None
    ) -> list[dict]:
        return self._post(
            "/v1/retrieve",
            {
                "query": query,
                "k": k,
                "metadata_filter": metadata_filter,
                "filepath_globpattern": filepath_globpattern,
            },
        )

    __call__ = query

    def get_vectorstore_statistics(self) -> dict:
        return self._post("/v1/statistics", {})

    def get_input_files(
        self, metadata_filter: str | None = None, filepath_globpattern: str | None = None
    ) -> list:
        return self._post(
            "/v1/inputs",
            {"metadata_filter": metadata_filter, "filepath_globpattern": filepath_globpattern},
        )
