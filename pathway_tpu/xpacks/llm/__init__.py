"""LLM/RAG xpack (parity: python/pathway/xpacks/llm/, 8k LoC).

Embedders and rerankers run as jit-compiled Flax models with epoch-batched
device dispatch; indexes keep their matrices device-resident; REST servers
ride the streaming engine.
"""

from pathway_tpu.xpacks.llm import (
    embedders,
    llms,
    parsers,
    prompts,
    rerankers,
    servers,
    splitters,
)
from pathway_tpu.xpacks.llm.document_store import DocumentStore, SlidesDocumentStore
from pathway_tpu.xpacks.llm.question_answering import (
    AdaptiveRAGQuestionAnswerer,
    BaseContextProcessor,
    BaseRAGQuestionAnswerer,
    DeckRetriever,
    RAGClient,
    SimpleContextProcessor,
    SummaryQuestionAnswerer,
    send_post_request,
)
from pathway_tpu.xpacks.llm.vector_store import (
    SlidesVectorStoreServer,
    VectorStoreClient,
    VectorStoreServer,
)

__all__ = [
    "embedders",
    "llms",
    "parsers",
    "prompts",
    "rerankers",
    "servers",
    "splitters",
    "DocumentStore",
    "SlidesDocumentStore",
    "AdaptiveRAGQuestionAnswerer",
    "BaseContextProcessor",
    "BaseRAGQuestionAnswerer",
    "DeckRetriever",
    "RAGClient",
    "SimpleContextProcessor",
    "SummaryQuestionAnswerer",
    "send_post_request",
    "SlidesVectorStoreServer",
    "VectorStoreClient",
    "VectorStoreServer",
]
