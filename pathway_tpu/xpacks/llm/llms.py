"""Chat models (parity: xpacks/llm/llms.py:97-547).

OpenAI/LiteLLM/Cohere chats are API-gated; ``HFPipelineChat`` runs a local
transformers pipeline when a model is cached.  ``prompt_chat_single_qa``
mirrors the reference helper.  All chats are async UDFs so concurrent rows
of an epoch fan out together.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.types import Json
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.udfs import UDF, async_executor
import pathway_tpu.internals.expression as expr_mod


class BaseChat(UDF):
    """Common surface: __call__(messages) where messages is a chat list."""

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return True


def _messages_to_prompt(messages: Any) -> str:
    if isinstance(messages, Json):
        messages = messages.value
    if isinstance(messages, str):
        return messages
    if isinstance(messages, (list, tuple)):
        parts = []
        for m in messages:
            if isinstance(m, Json):
                m = m.value
            if isinstance(m, dict):
                parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}")
            else:
                parts.append(str(m))
        return "\n".join(parts)
    return str(messages)


class OpenAIChat(BaseChat):
    """OpenAI chat (parity: llms.py:97). Gated on `openai`."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "gpt-3.5-turbo",
        retry_strategy=None,
        cache_strategy=None,
        **openai_kwargs,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(openai_kwargs)

        async def chat(messages: Any, **kwargs) -> str | None:
            import openai  # gated

            client = openai.AsyncOpenAI()
            if isinstance(messages, Json):
                messages = messages.value
            if isinstance(messages, str):
                messages = [{"role": "user", "content": messages}]
            params = {"model": self.model, **self.kwargs, **kwargs}
            ret = await client.chat.completions.create(messages=messages, **params)
            return ret.choices[0].message.content

        self.__wrapped__ = chat


class LiteLLMChat(BaseChat):
    """LiteLLM chat (parity: llms.py). Gated on `litellm`."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy=None,
        cache_strategy=None,
        **litellm_kwargs,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(litellm_kwargs)

        async def chat(messages: Any, **kwargs) -> str | None:
            import litellm  # gated

            if isinstance(messages, Json):
                messages = messages.value
            if isinstance(messages, str):
                messages = [{"role": "user", "content": messages}]
            ret = await litellm.acompletion(
                model=self.model, messages=messages, **{**self.kwargs, **kwargs}
            )
            return ret.choices[0]["message"]["content"]

        self.__wrapped__ = chat


class CohereChat(BaseChat):
    """Cohere chat with citations (parity: llms.py:~547). Gated on `cohere`."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "command",
        retry_strategy=None,
        cache_strategy=None,
        **cohere_kwargs,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(cohere_kwargs)

        async def chat(messages: Any, documents=None, **kwargs) -> tuple:
            import cohere  # gated

            client = cohere.AsyncClient()
            ret = await client.chat(
                message=_messages_to_prompt(messages),
                model=self.model,
                documents=documents,
                **{**self.kwargs, **kwargs},
            )
            cited = [dict(c.__dict__) for c in (ret.citations or [])]
            return (ret.text, tuple(map(str, cited)))

        self.__wrapped__ = chat


class HFPipelineChat(BaseChat):
    """Local transformers pipeline chat (parity: llms.py HFPipelineChat).

    Works offline when the model is in the local HF cache; the reference
    runs this on CPU/GPU torch — ``JaxChat`` below is the TPU-native
    serving path for the generation side.
    """

    def __init__(
        self,
        model: str | None = "gpt2",
        call_kwargs: dict = {},
        device: str = "cpu",
        **pipeline_kwargs,
    ):
        super().__init__()
        self.model = model
        self.call_kwargs = dict(call_kwargs)
        self.pipeline_kwargs = dict(pipeline_kwargs)
        self._pipeline = None

        def chat(messages: Any, **kwargs) -> str | None:
            pipe = self._get_pipeline()
            prompt = _messages_to_prompt(messages)
            out = pipe(prompt, **{**self.call_kwargs, **kwargs})
            text = out[0]["generated_text"]
            if isinstance(text, str) and text.startswith(prompt):
                text = text[len(prompt):]
            return text

        self.__wrapped__ = chat

    def _get_pipeline(self):
        if self._pipeline is None:
            import os

            os.environ.setdefault("HF_HUB_OFFLINE", "1")
            os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")
            from transformers import pipeline  # gated offline

            self._pipeline = pipeline(
                "text-generation", model=self.model, **self.pipeline_kwargs
            )
        return self._pipeline

    def crop_to_max_prompt_size(self, text: str, max_tokens: int = 1024) -> str:
        return text[: max_tokens * 4]


class JaxChat(BaseChat):
    """TPU-native local chat: jitted JAX decoder with a KV cache.

    The reference's local-serving story is a host-side torch pipeline
    (``xpacks/llm/llms.py:314`` HFPipelineChat; the Adaptive RAG template
    runs Mistral-7B-Instruct through it).  Here generation runs as two
    compiled XLA programs — bucketed-prompt prefill and a single-token
    decode step reused for every generated token (``models/decoder.py``) —
    so the serving path is device-resident end to end.  Concurrent rows of
    an epoch are micro-batched into one padded ragged generation batch.  A
    locally cached llama/mistral-family checkpoint is mapped in when
    present; otherwise deterministic random weights keep shapes/FLOPs (and
    thus serving latency) identical.
    """

    def __init__(
        self,
        model: str = "mistral-7b-instruct",
        max_new_tokens: int = 128,
        temperature: float = 0.0,
        max_cache: int = 1024,
        max_batch: int = 32,
        capacity: int | None = None,
        cache_strategy=None,
        quantize: str | None = None,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.max_cache = max_cache
        self.max_batch = max_batch
        if quantize not in (None, "int8"):  # fail at config time, not first row
            raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
        self.quantize = quantize
        self._model = None
        self._init_lock = None
        self._batchers: dict[tuple, Any] = {}

        async def chat(messages: Any, **kwargs) -> str:
            import asyncio

            from pathway_tpu.serving import generation

            if self._model is None:
                # first call compiles; keep the loop free while it does,
                # and hold a lock so concurrent rows build it only once
                if self._init_lock is None:
                    self._init_lock = asyncio.Lock()
                async with self._init_lock:
                    if self._model is None:
                        self._model = await asyncio.to_thread(self._build_model)
            lm = self._model
            mnt = int(kwargs.get("max_tokens", self.max_new_tokens))
            temp = float(kwargs.get("temperature", self.temperature))
            # coerce BEFORE keying: 5 and 5.0 must share one batcher (and
            # one compiled program), and a malformed kwarg should fail
            # here with a clear TypeError, not inside the batch worker
            top_k = kwargs.get("top_k")
            top_k = None if top_k is None else int(top_k)
            top_p = kwargs.get("top_p")
            top_p = None if top_p is None else float(top_p)
            min_p = kwargs.get("min_p")
            min_p = None if min_p is None else float(min_p)
            rep = kwargs.get("repetition_penalty")
            rep = None if rep is None else float(rep)
            # continuous batching: every sampling config shares ONE
            # scheduler batch (per-slot temp/top_p/min_p ride as data in
            # the compiled step), so a new config never waits for a
            # static batch to drain.  top_k / repetition_penalty need
            # per-row history state the fixed-shape step doesn't carry —
            # those configs fall back to the static batcher below.
            if (
                generation.continuous_enabled()
                and top_k is None
                and rep is None
            ):
                sched = generation.shared_scheduler(
                    self.model, max_cache=self.max_cache,
                    quantize=self.quantize,
                )
                fut = sched.submit(
                    _messages_to_prompt(messages),
                    max_new_tokens=mnt,
                    temperature=temp,
                    top_p=top_p,
                    min_p=min_p,
                )
                return await asyncio.wrap_future(fut)
            bkey = (mnt, temp, top_k, top_p, min_p, rep)
            batcher = self._batchers.get(bkey)
            if batcher is None:
                from pathway_tpu.utils.batching import AsyncMicroBatcher

                # one batcher per sampling config; generation is seconds
                # long, so batches run in a thread to keep the loop live
                batcher = AsyncMicroBatcher(
                    lambda prompts: lm.generate_many(
                        prompts,
                        max_new_tokens=mnt,
                        temperature=temp,
                        top_k=top_k,
                        top_p=top_p,
                        min_p=min_p,
                        repetition_penalty=rep,
                    ),
                    max_batch_size=self.max_batch,
                    flush_delay=0.01,
                    run_in_thread=True,
                )
                self._batchers[bkey] = batcher
            return await batcher.submit(_messages_to_prompt(messages))

        self.__wrapped__ = chat

    def _build_model(self):
        from pathway_tpu.models.decoder import shared_decoder

        return shared_decoder(
            self.model, max_cache=self.max_cache, quantize=self.quantize
        )

    def crop_to_max_prompt_size(self, text: str, max_tokens: int = 1024) -> str:
        return text[: max_tokens * 4]


def prompt_chat_single_qa(question: ColumnExpression) -> ColumnExpression:
    """Wrap a question column into a single-message chat (llms.py helper)."""
    from pathway_tpu.internals import dtype as dt

    return expr_mod.ApplyExpression(
        lambda q: Json([{"role": "user", "content": q}]),
        dt.JSON,
        question,
    )
