"""Text splitters (parity: xpacks/llm/splitters.py).

``TokenCountSplitter`` — token-budgeted chunks with soft boundaries;
``RecursiveSplitter`` — separator-hierarchy splitting (langchain-style, as
the reference wraps); ``NullSplitter`` — identity.
Splitters are UDFs returning tuple[(text, metadata)] chunks.
"""

from __future__ import annotations

import re
from typing import Any

from pathway_tpu.engine.types import Json
from pathway_tpu.internals.udfs import UDF


def null_splitter(txt: str) -> list[tuple[str, dict]]:
    """One chunk, the full text, empty metadata (reference splitters.py:13)."""
    return [(txt, {})]


def _to_text(data: Any) -> str:
    if isinstance(data, bytes):
        return data.decode("utf-8", errors="replace")
    if isinstance(data, Json):
        return str(data.value)
    return str(data)


class BaseSplitter(UDF):
    def chunk(self, text: str, metadata: dict | None = None) -> list[tuple[str, dict]]:
        raise NotImplementedError

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

        def split(text, metadata=None) -> tuple:
            meta = metadata.value if isinstance(metadata, Json) else (metadata or {})
            chunks = self.chunk(_to_text(text), dict(meta))
            return tuple((c, Json(m)) for (c, m) in chunks)

        self.__wrapped__ = split


class NullSplitter(BaseSplitter):
    """Identity splitter (parity: splitters.py NullSplitter)."""

    def chunk(self, text: str, metadata: dict | None = None) -> list[tuple[str, dict]]:
        return [(text, metadata or {})]


_WORDS = re.compile(r"\S+")


class TokenCountSplitter(BaseSplitter):
    """Split into chunks of [min_tokens, max_tokens] tokens, preferring to
    break at sentence/punctuation boundaries (parity: splitters.py
    TokenCountSplitter, tiktoken-based in the reference; token = whitespace
    word here unless a local HF tokenizer is available).

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter
    >>> split = TokenCountSplitter(min_tokens=2, max_tokens=3)
    >>> chunks = split.__wrapped__('one two three four five')
    >>> print([c[0] for c in chunks])
    ['one two three', 'four five']
    """

    def __init__(
        self,
        min_tokens: int = 50,
        max_tokens: int = 500,
        encoding_name: str = "cl100k_base",
        **kwargs,
    ):
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.encoding_name = encoding_name
        super().__init__(**kwargs)

    def chunk(self, text: str, metadata: dict | None = None) -> list[tuple[str, dict]]:
        metadata = metadata or {}
        words = _WORDS.findall(text)
        if not words:
            return []
        chunks: list[tuple[str, dict]] = []
        start = 0
        while start < len(words):
            end = min(start + self.max_tokens, len(words))
            # prefer a sentence boundary after min_tokens
            best = end
            if end < len(words):
                for j in range(end, max(start + self.min_tokens, start + 1) - 1, -1):
                    if words[j - 1].endswith((".", "!", "?", ";", ":")):
                        best = j
                        break
            chunk_words = words[start:best]
            chunks.append((" ".join(chunk_words), dict(metadata)))
            start = best
        return chunks


class RecursiveSplitter(BaseSplitter):
    """Recursive separator splitting with overlap (parity: splitters.py
    RecursiveSplitter wrapping langchain's RecursiveCharacterTextSplitter)."""

    def __init__(
        self,
        chunk_size: int = 500,
        chunk_overlap: int = 0,
        separators: list[str] | None = None,
        encoding_name: str = "cl100k_base",
        model_name: str | None = None,
        **kwargs,
    ):
        if chunk_size <= 0:
            raise ValueError(f"chunk_size must be positive, got {chunk_size}")
        if chunk_overlap < 0 or chunk_overlap >= chunk_size:
            raise ValueError(
                f"chunk_overlap ({chunk_overlap}) must be in [0, chunk_size)"
                f" — chunk_size is {chunk_size}"
            )
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.separators = separators or ["\n\n", "\n", ". ", " ", ""]
        super().__init__(**kwargs)

    def _split_rec(self, text: str, seps: list[str]) -> list[str]:
        if len(text) <= self.chunk_size:
            return [text] if text else []
        if not seps:
            return [
                text[i : i + self.chunk_size]
                for i in range(0, len(text), self.chunk_size - self.chunk_overlap or self.chunk_size)
            ]
        sep, rest = seps[0], seps[1:]
        if sep == "":
            return self._split_rec(text, rest) if rest else self._split_rec(text, [])
        parts = text.split(sep)
        chunks, cur = [], ""
        for part in parts:
            candidate = (cur + sep + part) if cur else part
            if len(candidate) <= self.chunk_size:
                cur = candidate
            else:
                if cur:
                    chunks.append(cur)
                if len(part) > self.chunk_size:
                    chunks.extend(self._split_rec(part, rest))
                    cur = ""
                else:
                    cur = part
        if cur:
            chunks.append(cur)
        if self.chunk_overlap and len(chunks) > 1:
            overlapped = [chunks[0]]
            for prev, nxt in zip(chunks, chunks[1:]):
                tail = prev[-self.chunk_overlap :]
                overlapped.append(tail + sep + nxt if tail else nxt)
            chunks = overlapped
        return chunks

    def chunk(self, text: str, metadata: dict | None = None) -> list[tuple[str, dict]]:
        return [(c, dict(metadata or {})) for c in self._split_rec(text, self.separators)]
