"""Prompt templates (parity: xpacks/llm/prompts.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.types import Json
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnExpression


def _docs_to_context(docs: Any) -> str:
    if isinstance(docs, Json):
        docs = docs.value
    parts = []
    for d in docs or ():
        if isinstance(d, Json):
            d = d.value
        if isinstance(d, dict):
            parts.append(str(d.get("text", d)))
        else:
            parts.append(str(d))
    return "\n\n".join(parts)


def prompt_short_qa(docs, query, additional_rules: str = "") -> ColumnExpression:
    r"""Build the short-answer QA prompt as a column expression.

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.xpacks.llm import prompts
    >>> t = pw.debug.table_from_markdown('q\nwhat_is_a_tpu')
    >>> r = t.select(p=prompts.prompt_short_qa(pw.make_tuple('doc one'), pw.this.q))
    >>> out = pw.debug.table_to_pandas(r, include_id=False)
    >>> print('Answer the question' in out['p'][0], 'doc one' in out['p'][0])
    False True
    """
    def build(docs_v, query_v) -> str:
        return (
            "Please provide an answer based solely on the provided sources. "
            "Keep your answer concise and accurate. "
            + additional_rules
            + f"\nSources:\n{_docs_to_context(docs_v)}\nQuestion: {query_v}\nAnswer:"
        )

    return ApplyExpression(build, str, docs, query)


def prompt_qa(
    docs,
    query,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
) -> ColumnExpression:
    def build(docs_v, query_v) -> str:
        return (
            "Please provide an answer based solely on the provided sources. "
            "When referencing information from a source, cite it. "
            f"If none of the sources are helpful, respond with: "
            f"{information_not_found_response} "
            + additional_rules
            + f"\nSources:\n{_docs_to_context(docs_v)}\nQuestion: {query_v}\nAnswer:"
        )

    return ApplyExpression(build, str, docs, query)


def prompt_qa_geometric_rag(
    docs,
    query,
    information_not_found_response: str = "No information found.",
    additional_rules: str = "",
) -> ColumnExpression:
    """The adaptive-RAG prompt (parity: prompts.py geometric rag prompt)."""

    def build(docs_v, query_v) -> str:
        context = _docs_to_context(docs_v)
        return (
            "Use the below articles to answer the subsequent question. If the "
            "answer cannot be found in the articles, write "
            f'"{information_not_found_response}" '
            + additional_rules
            + f"\nArticles:\n{context}\nQuestion: {query_v}\nAnswer:"
        )

    return ApplyExpression(build, str, docs, query)


def prompt_summarize(text_list) -> ColumnExpression:
    def build(texts) -> str:
        joined = "\n".join(str(t) for t in (texts or ()))
        return f"Summarize the following text concisely:\n{joined}\nSummary:"

    return ApplyExpression(build, str, text_list)


def prompt_query_rewrite_hyde(query) -> ColumnExpression:
    def build(q) -> str:
        return (
            "Write a short passage that would answer the question below "
            f"(hypothetical document embedding).\nQuestion: {q}\nPassage:"
        )

    return ApplyExpression(build, str, query)
