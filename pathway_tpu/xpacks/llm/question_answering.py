"""RAG question answering (parity: xpacks/llm/question_answering.py:97-788).

``BaseRAGQuestionAnswerer`` — retrieve top-k, prompt, answer.
``AdaptiveRAGQuestionAnswerer`` — geometric-k re-asking (:97-162): start
with few documents; if the model answers "No information found", double
the context and ask again.  ``SummaryQuestionAnswerer`` adds summarize.
``DeckRetriever`` — slide-deck retrieval app built on the same base.
"""

from __future__ import annotations

import asyncio
from typing import Any

import pathway_tpu as pw
from pathway_tpu.engine.types import Json
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.xpacks.llm import prompts
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.servers import QARestServer, QASummaryRestServer


class BaseQuestionAnswerer:
    AnswerQuerySchema: type[pw.Schema]
    RetrieveQuerySchema: type[pw.Schema]
    StatisticsQuerySchema: type[pw.Schema]
    InputsQuerySchema: type[pw.Schema]


class BaseRAGQuestionAnswerer(BaseQuestionAnswerer):
    """Standard RAG: retrieve → prompt → LLM (parity :288)."""

    class AnswerQuerySchema(pw.Schema):
        prompt: str
        filters: str | None
        model: str | None
        return_context_docs: bool | None

    class RetrieveQuerySchema(DocumentStore.RetrieveQuerySchema):
        pass

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(DocumentStore.InputsQuerySchema):
        pass

    class SummarizeQuerySchema(pw.Schema):
        text_list: Json
        model: str | None

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        default_llm_name: str | None = None,
        prompt_template=None,
        search_topk: int = 6,
        summarize_template=None,
    ):
        self.llm = llm
        self.indexer = indexer
        self.search_topk = search_topk
        self.prompt_template = prompt_template or prompts.prompt_qa
        self.summarize_template = summarize_template or prompts.prompt_summarize
        self.server: Any = None

    # -- internal: fetch docs for a query table --
    def _retrieve_docs(self, queries: Table, k: int | None = None) -> Table:
        augmented = queries.with_columns(
            query=ColumnReference(this, "prompt"),
            k=expr_mod.ColumnConstExpression(k or self.search_topk),
            metadata_filter=expr_mod.coalesce(
                ColumnReference(this, "filters"), None
            )
            if "filters" in queries.column_names()
            else expr_mod.ColumnConstExpression(None),
            filepath_globpattern=expr_mod.ColumnConstExpression(None),
        )
        replies = self.indexer.retrieve_query(augmented)
        return queries.with_columns(
            docs=replies.result,
        )

    def answer_query(self, pw_ai_queries: Table) -> Table:
        """The /v1/pw_ai_answer handler (parity :387)."""
        with_docs = self._retrieve_docs(pw_ai_queries)
        prompted = with_docs.with_columns(
            _pw_prompt=self.prompt_template(
                ColumnReference(this, "docs"), ColumnReference(this, "prompt")
            )
        )
        llm = self.llm

        answered = prompted.with_columns(
            _pw_answer=llm(
                ApplyExpression(
                    lambda p: Json([{"role": "user", "content": p}]),
                    None,
                    ColumnReference(this, "_pw_prompt"),
                )
            )
        )

        def pack(answer, docs, return_context_docs) -> Json:
            out: dict = {"response": answer}
            if return_context_docs:
                out["context_docs"] = docs.value if isinstance(docs, Json) else docs
            return Json(out)

        return answered.select(
            result=ApplyExpression(
                pack,
                None,
                ColumnReference(this, "_pw_answer"),
                ColumnReference(this, "docs"),
                ColumnReference(this, "return_context_docs")
                if "return_context_docs" in answered.column_names()
                else expr_mod.ColumnConstExpression(False),
                _propagate_none=False,
            )
        )

    pw_ai_query = answer_query  # legacy name (reference keeps both)

    def retrieve(self, retrieval_queries: Table) -> Table:
        return self.indexer.retrieve_query(retrieval_queries)

    def statistics(self, info_queries: Table) -> Table:
        return self.indexer.statistics_query(info_queries)

    def list_documents(self, input_queries: Table) -> Table:
        return self.indexer.inputs_query(input_queries)

    def summarize_query(self, summarize_queries: Table) -> Table:
        """The /v1/pw_ai_summary handler (parity :~460)."""
        prompted = summarize_queries.with_columns(
            _pw_prompt=self.summarize_template(
                ApplyExpression(
                    lambda tl: tuple(tl.value) if isinstance(tl, Json) else tuple(tl or ()),
                    None,
                    ColumnReference(this, "text_list"),
                )
            )
        )
        answered = prompted.with_columns(
            _pw_answer=self.llm(
                ApplyExpression(
                    lambda p: Json([{"role": "user", "content": p}]),
                    None,
                    ColumnReference(this, "_pw_prompt"),
                )
            )
        )
        return answered.select(
            result=ApplyExpression(
                lambda a: Json({"response": a}),
                None,
                ColumnReference(this, "_pw_answer"),
                _propagate_none=False,
            )
        )

    # -- serving --
    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        self.server = QASummaryRestServer(host, port, self, **rest_kwargs)

    def run_server(self, *args, **kwargs):
        if self.server is None:
            raise ValueError("call build_server(host, port) first")
        return self.server.run_server(*args, **kwargs)


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Geometric-k adaptive RAG (parity :97-162).

    Over-fetches ``max_context_docs`` once from the as-of-now index, then
    asks the LLM with n_starting_documents, doubling (factor) until the
    answer is not the not-found response — the prompt-side behavior of the
    reference's re-asking loop, with one index round-trip instead of many.
    """

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        default_llm_name: str | None = None,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        **kwargs,
    ):
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.not_found_response = "No information found."

    def answer_query(self, pw_ai_queries: Table) -> Table:
        max_docs = self.n_starting_documents * (
            self.factor ** (self.max_iterations - 1)
        )
        with_docs = self._retrieve_docs(pw_ai_queries, k=max_docs)
        # directly-awaitable form keeps the LLM UDF's retry/capacity/cache config
        llm_fn = self.llm.as_async_callable()
        n0, factor, rounds = self.n_starting_documents, self.factor, self.max_iterations
        not_found = self.not_found_response

        @pw.udf(executor=pw.udfs.async_executor())
        async def adaptive_answer(prompt: str, docs: Json) -> Json:
            doc_list = docs.value if isinstance(docs, Json) else list(docs or ())
            n = n0
            answer = not_found
            prev_size = -1
            for _round in range(rounds):
                subset = doc_list[:n]
                if len(subset) == prev_size:
                    break  # context exhausted; re-asking would repeat verbatim
                prev_size = len(subset)
                context = "\n\n".join(str(d.get("text", d)) for d in subset)
                full_prompt = (
                    "Use the below articles to answer the subsequent question. "
                    f'If the answer cannot be found, write "{not_found}"\n'
                    f"Articles:\n{context}\nQuestion: {prompt}\nAnswer:"
                )
                res = await llm_fn([{"role": "user", "content": full_prompt}])
                answer = res
                if res and not_found.lower().rstrip(".") not in str(res).lower():
                    break
                n = min(n * factor, len(doc_list))
            return Json({"response": answer})

        return with_docs.select(
            result=adaptive_answer(
                ColumnReference(this, "prompt"), ColumnReference(this, "docs")
            )
        )


class SummaryQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Alias emphasizing the summarization endpoints (parity)."""


class DeckRetriever(BaseQuestionAnswerer):
    """Slide-deck retrieval app (parity :288; search-only surface)."""

    class AnswerQuerySchema(pw.Schema):
        prompt: str
        filters: str | None

    class RetrieveQuerySchema(DocumentStore.RetrieveQuerySchema):
        pass

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(DocumentStore.InputsQuerySchema):
        pass

    def __init__(self, indexer: DocumentStore, *, search_topk: int = 6, **kwargs):
        self.indexer = indexer
        self.search_topk = search_topk
        self.server = None

    def answer_query(self, queries: Table) -> Table:
        augmented = queries.with_columns(
            query=ColumnReference(this, "prompt"),
            k=expr_mod.ColumnConstExpression(self.search_topk),
            metadata_filter=expr_mod.coalesce(ColumnReference(this, "filters"), None),
            filepath_globpattern=expr_mod.ColumnConstExpression(None),
        )
        return self.indexer.retrieve_query(augmented)

    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, q: Table) -> Table:
        return self.indexer.statistics_query(q)

    def list_documents(self, q: Table) -> Table:
        return self.indexer.inputs_query(q)

    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        self.server = QARestServer(host, port, self, **rest_kwargs)

    def run_server(self, *args, **kwargs):
        return self.server.run_server(*args, **kwargs)
