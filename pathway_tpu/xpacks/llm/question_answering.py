"""RAG question answering (parity: xpacks/llm/question_answering.py:97-1030).

``BaseRAGQuestionAnswerer`` — retrieve top-k, prompt, answer.
``AdaptiveRAGQuestionAnswerer`` — geometric-k re-asking (:97-162): start
with few documents; if the model answers "No information found", double
the context and ask again.  ``SummaryQuestionAnswerer`` adds summarize.
``DeckRetriever`` — slide-deck retrieval app built on the same base.
``BaseContextProcessor``/``SimpleContextProcessor`` (:221,:257) — pluggable
docs→context assembly.  ``RAGClient`` (:879) — HTTP client for the servers.
"""

from __future__ import annotations

import inspect
import json as _json
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any

import pathway_tpu as pw
from pathway_tpu.engine.types import Json
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.internals.udfs import UDF
from pathway_tpu.xpacks.llm import prompts
from pathway_tpu.xpacks.llm._utils import send_post_request
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.servers import QARestServer, QASummaryRestServer
from pathway_tpu.xpacks.llm.vector_store import VectorStoreClient


class BaseContextProcessor(ABC):
    """Formats retrieved documents into the LLM context string
    (parity: question_answering.py:221-252).

    Subclasses implement ``docs_to_context``; ``apply`` normalizes the
    incoming docs value (Json, list of Json, or list of dicts) first.
    """

    def maybe_unwrap_docs(self, docs) -> list:
        if isinstance(docs, Json):
            doc_ls = list(docs.value or ())
        elif isinstance(docs, (list, tuple)):
            doc_ls = [d.value if isinstance(d, Json) else d for d in docs]
        else:
            raise ValueError(
                "`docs` argument is not Json | list[Json] | list[dict]; "
                "check your pipeline (pw.reducers.tuple may help)"
            )
        if len(doc_ls) == 1 and isinstance(doc_ls[0], (list, tuple)):
            doc_ls = list(doc_ls[0])
        return [d.value if isinstance(d, Json) else d for d in doc_ls]

    def apply(self, docs) -> str:
        return self.docs_to_context(self.maybe_unwrap_docs(docs))

    @abstractmethod
    def docs_to_context(self, docs: list[dict]) -> str: ...

    def as_udf(self) -> UDF:
        u = UDF()
        u.__wrapped__ = self.apply
        return u


@dataclass
class SimpleContextProcessor(BaseContextProcessor):
    """Keeps the listed metadata keys and joins documents with the joiner
    (parity: question_answering.py:257-282).

    Example:

    >>> from pathway_tpu.xpacks.llm.question_answering import SimpleContextProcessor
    >>> proc = SimpleContextProcessor(context_metadata_keys=["path"])
    >>> docs = [
    ...     {"text": "alpha", "metadata": {"path": "/a.txt", "b64_image": "x"}},
    ...     {"text": "beta", "metadata": {"path": "/b.txt"}},
    ... ]
    >>> print(proc.apply(docs))
    {"text": "alpha", "path": "/a.txt"}
    <BLANKLINE>
    {"text": "beta", "path": "/b.txt"}
    """

    context_metadata_keys: list[str] = field(default_factory=lambda: ["path"])
    context_joiner: str = "\n\n"

    def simplify_context_metadata(self, docs: list[dict]) -> list[dict]:
        filtered = []
        for doc in docs:
            if not isinstance(doc, dict):
                filtered.append({"text": str(doc)})
                continue
            entry = {"text": doc.get("text", "")}
            metadata = doc.get("metadata", {}) or {}
            if isinstance(metadata, Json):
                metadata = metadata.value or {}
            for key in self.context_metadata_keys:
                if key in metadata:
                    entry[key] = metadata[key]
            filtered.append(entry)
        return filtered

    def docs_to_context(self, docs: list[dict]) -> str:
        docs = self.simplify_context_metadata(docs)
        return self.context_joiner.join(
            _json.dumps(doc, ensure_ascii=False) for doc in docs
        )


def _geometric_answer_udf(
    llm_chat_model,
    n_starting_documents: int,
    factor: int,
    max_iterations: int,
    strict_prompt: bool,
):
    """Async per-row geometric re-asking loop shared by the strategy
    functions and AdaptiveRAGQuestionAnswerer (parity :97-162 semantics:
    ask with k docs, multiply k by ``factor`` until answered or
    ``max_iterations`` reached; None when no answer is found)."""
    llm_fn = llm_chat_model.as_async_callable()
    not_found = "No information found."

    @pw.udf(executor=pw.udfs.async_executor())
    async def geometric_answer(question: str, docs: Json) -> str | None:
        doc_list = list(docs.value or ()) if isinstance(docs, Json) else list(docs or ())
        texts = [
            str(d.get("text", d)) if isinstance(d, dict) else str(d) for d in doc_list
        ]
        n = n_starting_documents
        prev_size = -1
        for _round in range(max_iterations):
            subset = texts[:n]
            if len(subset) == prev_size:
                break  # context exhausted; re-asking would repeat verbatim
            prev_size = len(subset)
            context = "\n\n".join(subset)
            if strict_prompt:
                full_prompt = (
                    "Use the below articles to answer the subsequent question. "
                    f'Respond with json of the form {{"answer": "..."}}; if the '
                    f'answer cannot be found, use "{not_found}".\n'
                    f"Articles:\n{context}\nQuestion: {question}"
                )
            else:
                full_prompt = (
                    "Use the below articles to answer the subsequent question. "
                    f'If the answer cannot be found, write "{not_found}"\n'
                    f"Articles:\n{context}\nQuestion: {question}\nAnswer:"
                )
            res = await llm_fn([{"role": "user", "content": full_prompt}])
            answer = str(res) if res is not None else ""
            if strict_prompt and "{" in answer:
                try:
                    payload = _json.loads(answer[answer.find("{") : answer.find("}") + 1])
                    answer = " ".join(str(v) for v in payload.values())
                except (ValueError, AttributeError):
                    pass
            if answer and not_found.lower().rstrip(".") not in answer.lower():
                return answer
            n = min(n * factor, len(texts))
        return None

    return geometric_answer


def answer_with_geometric_rag_strategy(
    questions: ColumnReference,
    documents: ColumnReference,
    llm_chat_model,
    n_starting_documents: int,
    factor: int,
    max_iterations: int,
    strict_prompt: bool = False,
) -> ColumnReference:
    """Query the LLM with geometrically growing document context until an
    answer is found (parity: question_answering.py:97-159).  Returns a
    column of answers; None where no answer was found."""
    geometric_answer = _geometric_answer_udf(
        llm_chat_model, n_starting_documents, factor, max_iterations, strict_prompt
    )
    table = questions.table
    # like the reference, the result table carries query/documents through
    # so callers can select alongside the answer column
    result = table.select(
        query=questions,
        documents=documents,
        answer=geometric_answer(questions, documents),
    )
    return result.answer


def answer_with_geometric_rag_strategy_from_index(
    questions: ColumnReference,
    index,
    documents_column,
    llm_chat_model,
    n_starting_documents: int,
    factor: int,
    max_iterations: int,
    metadata_filter=None,
    strict_prompt: bool = False,
) -> ColumnReference:
    """Like :func:`answer_with_geometric_rag_strategy` but over-fetches the
    documents once from ``index`` (parity: question_answering.py:162-218)."""
    if isinstance(documents_column, ColumnReference):
        documents_column_name = documents_column.name
    else:
        documents_column_name = documents_column
    max_documents = n_starting_documents * (factor ** (max_iterations - 1))
    # one over-fetch at the final context size; the reply table lives on the
    # query universe with the data columns collapsed to ranked tuples
    matches = index.query_as_of_now(
        questions,
        number_of_matches=max_documents,
        collapse_rows=True,
        metadata_filter=metadata_filter,
    )
    return answer_with_geometric_rag_strategy(
        ColumnReference(matches, questions.name),
        ColumnReference(matches, documents_column_name),
        llm_chat_model,
        n_starting_documents,
        factor,
        max_iterations,
        strict_prompt=strict_prompt,
    )


class BaseQuestionAnswerer:
    AnswerQuerySchema: type[pw.Schema]
    RetrieveQuerySchema: type[pw.Schema]
    StatisticsQuerySchema: type[pw.Schema]
    InputsQuerySchema: type[pw.Schema]


class BaseRAGQuestionAnswerer(BaseQuestionAnswerer):
    """Standard RAG: retrieve → prompt → LLM (parity :288)."""

    class AnswerQuerySchema(pw.Schema):
        prompt: str
        filters: str | None
        model: str | None
        return_context_docs: bool | None

    class RetrieveQuerySchema(DocumentStore.RetrieveQuerySchema):
        pass

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(DocumentStore.InputsQuerySchema):
        pass

    class SummarizeQuerySchema(pw.Schema):
        text_list: Json
        model: str | None

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        default_llm_name: str | None = None,
        prompt_template=None,
        context_processor=None,
        search_topk: int = 6,
        summarize_template=None,
    ):
        self.llm = llm
        self.indexer = indexer
        self.search_topk = search_topk
        self.prompt_template = prompt_template or prompts.prompt_qa
        if context_processor is None:
            context_processor = SimpleContextProcessor()
        if isinstance(context_processor, BaseContextProcessor):
            self.docs_to_context_transformer = context_processor.as_udf()
        elif isinstance(context_processor, UDF):
            self.docs_to_context_transformer = context_processor
        elif callable(context_processor):
            u = UDF()
            u.__wrapped__ = context_processor
            self.docs_to_context_transformer = u
        else:
            raise ValueError(
                "context_processor must be BaseContextProcessor | Callable | UDF, "
                f"got {type(context_processor)}"
            )
        self.summarize_template = summarize_template or prompts.prompt_summarize
        self.server: Any = None

    def _prompt_expr(self, docs_ref, query_ref):
        """Build the prompt column from docs + query.

        A ``str`` template (reference ``RAGPromptTemplate`` form) and any
        callable taking a ``context`` parameter go through the pluggable
        context processor; legacy repo templates taking ``docs`` receive
        the raw docs value and assemble context themselves.
        """
        template = self.prompt_template
        if isinstance(template, str):
            if "{context}" not in template or "{query}" not in template:
                raise ValueError(
                    "string prompt_template must contain {context} and {query}"
                )
            ctx = self.docs_to_context_transformer(docs_ref)
            return ApplyExpression(
                lambda c, q: template.format(context=c, query=q), str, ctx, query_ref
            )
        fn = template.__wrapped__ if isinstance(template, UDF) else template
        try:
            params = list(inspect.signature(fn).parameters)
        except (TypeError, ValueError):
            params = []
        if params and params[0] == "context":
            ctx = self.docs_to_context_transformer(docs_ref)
            return template(ctx, query_ref)
        return template(docs_ref, query_ref)

    # -- internal: fetch docs for a query table --
    def _retrieve_docs(self, queries: Table, k: int | None = None) -> Table:
        augmented = queries.with_columns(
            query=ColumnReference(this, "prompt"),
            k=expr_mod.ColumnConstExpression(k or self.search_topk),
            metadata_filter=expr_mod.coalesce(
                ColumnReference(this, "filters"), None
            )
            if "filters" in queries.column_names()
            else expr_mod.ColumnConstExpression(None),
            filepath_globpattern=expr_mod.ColumnConstExpression(None),
        )
        replies = self.indexer.retrieve_query(augmented)
        return queries.with_columns(
            docs=replies.result,
        )

    def answer_query(self, pw_ai_queries: Table) -> Table:
        """The /v1/pw_ai_answer handler (parity :387)."""
        with_docs = self._retrieve_docs(pw_ai_queries)
        prompted = with_docs.with_columns(
            _pw_prompt=self._prompt_expr(
                ColumnReference(this, "docs"), ColumnReference(this, "prompt")
            )
        )
        llm = self.llm

        answered = prompted.with_columns(
            _pw_answer=llm(
                ApplyExpression(
                    lambda p: Json([{"role": "user", "content": p}]),
                    None,
                    ColumnReference(this, "_pw_prompt"),
                )
            )
        )

        def pack(answer, docs, return_context_docs) -> Json:
            out: dict = {"response": answer}
            if return_context_docs:
                out["context_docs"] = docs.value if isinstance(docs, Json) else docs
            return Json(out)

        return answered.select(
            result=ApplyExpression(
                pack,
                None,
                ColumnReference(this, "_pw_answer"),
                ColumnReference(this, "docs"),
                ColumnReference(this, "return_context_docs")
                if "return_context_docs" in answered.column_names()
                else expr_mod.ColumnConstExpression(False),
                _propagate_none=False,
            )
        )

    pw_ai_query = answer_query  # legacy name (reference keeps both)

    def retrieve(self, retrieval_queries: Table) -> Table:
        return self.indexer.retrieve_query(retrieval_queries)

    def statistics(self, info_queries: Table) -> Table:
        return self.indexer.statistics_query(info_queries)

    def list_documents(self, input_queries: Table) -> Table:
        return self.indexer.inputs_query(input_queries)

    def summarize_query(self, summarize_queries: Table) -> Table:
        """The /v1/pw_ai_summary handler (parity :~460)."""
        prompted = summarize_queries.with_columns(
            _pw_prompt=self.summarize_template(
                ApplyExpression(
                    lambda tl: tuple(tl.value) if isinstance(tl, Json) else tuple(tl or ()),
                    None,
                    ColumnReference(this, "text_list"),
                )
            )
        )
        answered = prompted.with_columns(
            _pw_answer=self.llm(
                ApplyExpression(
                    lambda p: Json([{"role": "user", "content": p}]),
                    None,
                    ColumnReference(this, "_pw_prompt"),
                )
            )
        )
        return answered.select(
            result=ApplyExpression(
                lambda a: Json({"response": a}),
                None,
                ColumnReference(this, "_pw_answer"),
                _propagate_none=False,
            )
        )

    # -- serving --
    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        self.server = QASummaryRestServer(host, port, self, **rest_kwargs)

    def run_server(self, *args, **kwargs):
        if self.server is None:
            raise ValueError("call build_server(host, port) first")
        return self.server.run_server(*args, **kwargs)


class AdaptiveRAGQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Geometric-k adaptive RAG (parity :97-162).

    Over-fetches ``max_context_docs`` once from the as-of-now index, then
    asks the LLM with n_starting_documents, doubling (factor) until the
    answer is not the not-found response — the prompt-side behavior of the
    reference's re-asking loop, with one index round-trip instead of many.
    """

    def __init__(
        self,
        llm,
        indexer: DocumentStore,
        *,
        default_llm_name: str | None = None,
        n_starting_documents: int = 2,
        factor: int = 2,
        max_iterations: int = 4,
        strict_prompt: bool = False,
        **kwargs,
    ):
        super().__init__(llm, indexer, **kwargs)
        self.n_starting_documents = n_starting_documents
        self.factor = factor
        self.max_iterations = max_iterations
        self.strict_prompt = strict_prompt
        self.not_found_response = "No information found."

    def answer_query(self, pw_ai_queries: Table) -> Table:
        max_docs = self.n_starting_documents * (
            self.factor ** (self.max_iterations - 1)
        )
        with_docs = self._retrieve_docs(pw_ai_queries, k=max_docs)
        adaptive_answer = _geometric_answer_udf(
            self.llm,
            self.n_starting_documents,
            self.factor,
            self.max_iterations,
            self.strict_prompt,
        )
        not_found = self.not_found_response

        answered = with_docs.with_columns(
            _pw_answer=adaptive_answer(
                ColumnReference(this, "prompt"), ColumnReference(this, "docs")
            )
        )
        return answered.select(
            result=ApplyExpression(
                lambda a: Json({"response": a if a is not None else not_found}),
                None,
                ColumnReference(this, "_pw_answer"),
                _propagate_none=False,
            )
        )


class SummaryQuestionAnswerer(BaseRAGQuestionAnswerer):
    """Alias emphasizing the summarization endpoints (parity)."""


class DeckRetriever(BaseQuestionAnswerer):
    """Slide-deck retrieval app (parity :288; search-only surface)."""

    class AnswerQuerySchema(pw.Schema):
        prompt: str
        filters: str | None

    class RetrieveQuerySchema(DocumentStore.RetrieveQuerySchema):
        pass

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(DocumentStore.InputsQuerySchema):
        pass

    def __init__(self, indexer: DocumentStore, *, search_topk: int = 6, **kwargs):
        self.indexer = indexer
        self.search_topk = search_topk
        self.server = None

    def answer_query(self, queries: Table) -> Table:
        augmented = queries.with_columns(
            query=ColumnReference(this, "prompt"),
            k=expr_mod.ColumnConstExpression(self.search_topk),
            metadata_filter=expr_mod.coalesce(ColumnReference(this, "filters"), None),
            filepath_globpattern=expr_mod.ColumnConstExpression(None),
        )
        return self.indexer.retrieve_query(augmented)

    def retrieve(self, queries: Table) -> Table:
        return self.indexer.retrieve_query(queries)

    def statistics(self, q: Table) -> Table:
        return self.indexer.statistics_query(q)

    def list_documents(self, q: Table) -> Table:
        return self.indexer.inputs_query(q)

    def build_server(self, host: str, port: int, **rest_kwargs) -> None:
        self.server = QARestServer(host, port, self, **rest_kwargs)

    def run_server(self, *args, **kwargs):
        return self.server.run_server(*args, **kwargs)


class RAGClient:
    """HTTP client for the RAG question-answering servers
    (parity: question_answering.py:879-1030).

    Either (``host`` and ``port``) or ``url`` must be set, not both.
    """

    def __init__(
        self,
        host: str | None = None,
        port: int | None = None,
        url: str | None = None,
        timeout: int | None = 90,
        additional_headers: dict | None = None,
    ):
        err = "Either (`host` and `port`) or `url` must be provided, but not both."
        if url is not None:
            if host is not None or port is not None:
                raise ValueError(err)
            self.url = url
        else:
            if host is None:
                raise ValueError(err)
            port = port or 80
            protocol = "https" if port == 443 else "http"
            self.url = f"{protocol}://{host}:{port}"
        self.timeout = timeout
        self.additional_headers = additional_headers or {}
        self.index_client = VectorStoreClient(
            url=self.url,
            timeout=self.timeout,
            additional_headers=self.additional_headers,
        )

    def retrieve(
        self,
        query: str,
        k: int = 3,
        metadata_filter: str | None = None,
        filepath_globpattern: str | None = None,
    ):
        """Retrieve the k closest documents for ``query``."""
        return self.index_client.query(
            query=query,
            k=k,
            metadata_filter=metadata_filter,
            filepath_globpattern=filepath_globpattern,
        )

    def statistics(self):
        """Index statistics from the /v1/statistics endpoint."""
        return self.index_client.get_vectorstore_statistics()

    def pw_ai_answer(
        self,
        prompt: str,
        filters: str | None = None,
        model: str | None = None,
        return_context_docs: bool | None = None,
    ):
        """Ask the RAG app a question (POST /v1/pw_ai_answer)."""
        payload: dict = {"prompt": prompt}
        if filters:
            payload["filters"] = filters
        if model:
            payload["model"] = model
        if return_context_docs is not None:
            payload["return_context_docs"] = return_context_docs
        return send_post_request(
            f"{self.url}/v1/pw_ai_answer",
            payload,
            self.additional_headers,
            self.timeout,
        )

    answer = pw_ai_answer

    def pw_ai_summary(self, text_list: list[str], model: str | None = None):
        """Summarize a list of texts (POST /v1/pw_ai_summary)."""
        payload: dict = {"text_list": text_list}
        if model:
            payload["model"] = model
        return send_post_request(
            f"{self.url}/v1/pw_ai_summary",
            payload,
            self.additional_headers,
            self.timeout,
        )

    summarize = pw_ai_summary

    def pw_list_documents(
        self, filters: str | None = None, keys: list[str] | None = ["path"]
    ):
        """List indexed documents (POST /v1/pw_list_documents), keeping
        only ``keys`` from each document's metadata."""
        payload: dict = {}
        if filters:
            payload["metadata_filter"] = filters
        response = send_post_request(
            f"{self.url}/v1/pw_list_documents",
            payload,
            self.additional_headers,
            self.timeout,
        )
        if not response:
            return []
        if keys:
            return [{k: v for k, v in dc.items() if k in keys} for dc in response]
        return response

    list_documents = pw_list_documents
