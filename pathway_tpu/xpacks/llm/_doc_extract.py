"""Self-contained document text extraction (stdlib only).

The reference parses PDFs/DOCX/PPTX through heavyweight optional
dependencies (``unstructured``, ``docling``, ``pypdf`` —
``/root/reference/python/pathway/xpacks/llm/parsers.py``).  None of those
ship in this image, so DocumentStore could not ingest real documents.
These extractors cover the dominant formats with the standard library:

* PDF text lives mostly in FlateDecode content streams whose text
  operators (``Tj``/``TJ``/``'``/``"``) carry the strings — a small
  object parser + ``zlib`` recovers them per page;
* DOCX/PPTX are zip archives of WordprocessingML / PresentationML — the
  text is the ``<w:t>`` / ``<a:t>`` runs of ``word/document.xml`` /
  ``ppt/slides/slideN.xml``.

Scope: text extraction for standard one-byte encodings (the classic PDF
base fonts); embedded-CMap subset fonts decode best-effort.  That matches
what the fixture corpus and typical machine-generated reports need.
"""

from __future__ import annotations

import io
import re
import zipfile
import zlib
from xml.etree import ElementTree as ET

# ---------------------------------------------------------------------------
# PDF
# ---------------------------------------------------------------------------

_OBJ_RE = re.compile(rb"(\d+)\s+(\d+)\s+obj\b(.*?)endobj", re.S)
_STREAM_RE = re.compile(rb"stream\r?\n(.*?)\r?\nendstream", re.S)
_REF_RE = re.compile(rb"/Contents\s*(?:(\d+)\s+\d+\s+R|\[(.*?)\])", re.S)
_KIDS_RE = re.compile(rb"/Kids\s*\[(.*?)\]", re.S)
_NUM_REF_RE = re.compile(rb"(\d+)\s+\d+\s+R")


class PdfError(ValueError):
    pass


def _parse_objects(data: bytes) -> dict[int, bytes]:
    objs: dict[int, bytes] = {}
    for m in _OBJ_RE.finditer(data):
        objs[int(m.group(1))] = m.group(3)
    if not objs:
        raise PdfError("no PDF objects found")
    return objs


def _object_stream(body: bytes) -> bytes | None:
    m = _STREAM_RE.search(body)
    if m is None:
        return None
    raw = m.group(1)
    if b"/FlateDecode" in body[: m.start()]:
        try:
            return zlib.decompress(raw)
        except zlib.error as exc:
            raise PdfError(f"bad FlateDecode stream: {exc}") from None
    return raw


def _page_objects(objs: dict[int, bytes]) -> list[int]:
    """Page object numbers in page-tree order (fallback: document order)."""
    pages_nodes = {
        num
        for num, body in objs.items()
        if b"/Type" in body and re.search(rb"/Type\s*/Pages\b", body)
    }
    # intermediate /Pages nodes are Kids of another /Pages node — walking
    # them as roots would extract their subtree once per ancestor
    kids_of_pages: set[int] = set()
    for num in pages_nodes:
        kids = _KIDS_RE.search(objs[num])
        if kids:
            kids_of_pages.update(
                int(r.group(1)) for r in _NUM_REF_RE.finditer(kids.group(1))
            )
    roots = sorted(pages_nodes - kids_of_pages) or sorted(pages_nodes)
    pages_in_order: list[int] = []
    visited: set[int] = set()

    def walk(num: int) -> None:
        if num in visited:
            return
        visited.add(num)
        body = objs.get(num)
        if body is None:
            return
        if re.search(rb"/Type\s*/Page\b(?!s)", body):
            pages_in_order.append(num)
            return
        kids = _KIDS_RE.search(body)
        if kids:
            for ref in _NUM_REF_RE.finditer(kids.group(1)):
                walk(int(ref.group(1)))

    for root in roots:
        walk(root)
    if not pages_in_order:
        pages_in_order = [
            num
            for num, body in sorted(objs.items())
            if re.search(rb"/Type\s*/Page\b(?!s)", body)
        ]
    return pages_in_order


_ESCAPES = {
    ord("n"): "\n",
    ord("r"): "\r",
    ord("t"): "\t",
    ord("b"): "\b",
    ord("f"): "\f",
    ord("("): "(",
    ord(")"): ")",
    ord("\\"): "\\",
}


def _content_text(stream: bytes) -> str:
    """Pull the text operators out of one decoded content stream.

    Handles literal strings (with escapes and octal), hex strings, the
    ``Tj``/``'``/``"``/``TJ`` show operators, and emits newlines at the
    line-movement operators (``Td``/``TD``/``T*``) and text-object ends.
    TJ kerning numbers below -200/1000 em are rendered as a space (the
    convention most extractors use for inter-word gaps).
    """
    out: list[str] = []
    # operands in order: ("s", text) or ("n", number) — TJ needs the
    # interleaving to know which kerning gap sits between which strings
    operands: list[tuple[str, object]] = []
    i, n = 0, len(stream)

    def newline() -> None:
        if out and not out[-1].endswith("\n"):
            out.append("\n")

    while i < n:
        c = stream[i : i + 1]
        if c == b"(":
            depth = 1
            i += 1
            buf: list[str] = []
            while i < n and depth:
                b = stream[i]
                if b == 0x5C:  # backslash
                    i += 1
                    if i >= n:
                        break
                    e = stream[i]
                    if 0x30 <= e <= 0x37:  # octal, up to 3 digits
                        oct_digits = chr(e)
                        for _ in range(2):
                            if i + 1 < n and 0x30 <= stream[i + 1] <= 0x37:
                                i += 1
                                oct_digits += chr(stream[i])
                        buf.append(chr(int(oct_digits, 8)))
                    elif e in _ESCAPES:
                        buf.append(_ESCAPES[e])
                    elif e in (0x0A, 0x0D):
                        pass  # line continuation
                    else:
                        buf.append(chr(e))
                    i += 1
                    continue
                if b == 0x28:
                    depth += 1
                elif b == 0x29:
                    depth -= 1
                    if depth == 0:
                        i += 1
                        break
                buf.append(chr(b))
                i += 1
            operands.append(("s", "".join(buf)))
            continue
        if c == b"<" and stream[i : i + 2] != b"<<":
            j = stream.find(b">", i)
            if j < 0:
                break
            hexstr = re.sub(rb"\s", b"", stream[i + 1 : j])
            if len(hexstr) % 2:
                hexstr += b"0"
            try:
                operands.append(
                    ("s", bytes.fromhex(hexstr.decode()).decode("latin-1"))
                )
            except ValueError:
                pass
            i = j + 1
            continue
        if c == b"[":
            i += 1
            continue
        if c == b"]":
            i += 1
            continue
        m = re.match(rb"[-+]?\d*\.?\d+", stream[i : i + 24])
        if m and m.group(0) not in (b"", b"-", b"+"):
            try:
                operands.append(("n", float(m.group(0))))
            except ValueError:
                pass
            i += len(m.group(0))
            continue
        m = re.match(rb"[A-Za-z'\"*]+", stream[i : i + 8])
        if m:
            op = m.group(0)
            if op in (b"Tj", b"'", b'"'):
                if op != b"Tj":
                    newline()
                out.extend(str(v) for kind, v in operands if kind == "s")
            elif op == b"TJ":
                # kerning below -200/1000 em reads as an inter-word gap
                for kind, v in operands:
                    if kind == "s":
                        out.append(str(v))
                    elif float(v) < -200:
                        if out and not out[-1].endswith((" ", "\n")):
                            out.append(" ")
            elif op in (b"Td", b"TD", b"T*", b"ET"):
                newline()
            operands = []
            i += len(op)
            continue
        i += 1
    return "".join(out)


def pdf_extract_pages(data: bytes) -> list[str]:
    """Extract text per page from a PDF byte string."""
    if not data.startswith(b"%PDF"):
        raise PdfError("not a PDF (missing %PDF header)")
    objs = _parse_objects(data)
    pages: list[str] = []
    for num in _page_objects(objs):
        body = objs[num]
        content_ids: list[int] = []
        m = _REF_RE.search(body)
        if m:
            if m.group(1):
                content_ids.append(int(m.group(1)))
            else:
                content_ids.extend(
                    int(r.group(1)) for r in _NUM_REF_RE.finditer(m.group(2))
                )
        # the single-ref form may point at an array object of stream refs
        # (the legal indirect-array variant) — expand one level
        expanded: list[int] = []
        for cid in content_ids:
            body_c = objs.get(cid, b"")
            if b"stream" not in body_c and body_c.strip().startswith(b"["):
                expanded.extend(
                    int(r.group(1)) for r in _NUM_REF_RE.finditer(body_c)
                )
            else:
                expanded.append(cid)
        texts = []
        for cid in expanded:
            if cid in objs:
                stream = _object_stream(objs[cid])
                if stream:
                    texts.append(_content_text(stream))
        pages.append("".join(texts).strip())
    if not pages:
        # no page tree found — fall back to every stream that looks like a
        # content stream, as one page
        chunks = []
        for _num, body in sorted(objs.items()):
            stream = _object_stream(body)
            if stream and (b"Tj" in stream or b"TJ" in stream):
                chunks.append(_content_text(stream))
        if not chunks:
            raise PdfError("no text content streams found")
        pages = ["".join(chunks).strip()]
    return pages


def pdf_extract_text(data: bytes) -> str:
    return "\n\n".join(pdf_extract_pages(data)).strip()


# ---------------------------------------------------------------------------
# DOCX / PPTX (Office Open XML zip packages)
# ---------------------------------------------------------------------------


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def docx_extract_text(data: bytes) -> str:
    """Paragraph text of a .docx (WordprocessingML) package."""
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        xml = zf.read("word/document.xml")
    root = ET.fromstring(xml)
    paragraphs: list[str] = []
    for p in root.iter():
        if _local(p.tag) != "p":
            continue
        runs: list[str] = []
        for node in p.iter():
            tag = _local(node.tag)
            if tag == "t" and node.text:
                runs.append(node.text)
            elif tag == "tab":
                runs.append("\t")
            elif tag == "br":
                runs.append("\n")
        if runs:
            paragraphs.append("".join(runs))
    return "\n".join(paragraphs)


def pptx_extract_slides(data: bytes) -> list[str]:
    """Per-slide text of a .pptx (PresentationML) package, slide order."""
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        slide_names = sorted(
            (n for n in zf.namelist() if re.fullmatch(r"ppt/slides/slide\d+\.xml", n)),
            key=lambda n: int(re.search(r"(\d+)\.xml$", n).group(1)),
        )
        slides: list[str] = []
        for name in slide_names:
            root = ET.fromstring(zf.read(name))
            texts = [
                node.text
                for node in root.iter()
                if _local(node.tag) == "t" and node.text
            ]
            slides.append("\n".join(texts))
    return slides


def pptx_extract_text(data: bytes) -> str:
    return "\n\n".join(pptx_extract_slides(data)).strip()
