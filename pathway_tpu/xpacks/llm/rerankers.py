"""Rerankers (parity: xpacks/llm/rerankers.py:58-322).

``CrossEncoderReranker`` is the second jitted device model of the north
star: (query, doc) pairs are scored by the Flax cross-encoder through the
async micro-batcher.  ``LLMReranker`` asks a chat model for a 1-5 score;
``EncoderReranker`` scores by bi-encoder cosine; ``rerank_topk_filter``
mirrors the reference helper.
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

from pathway_tpu.engine.types import Json
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnExpression
from pathway_tpu.internals.udfs import UDF, async_executor
from pathway_tpu.utils.batching import AsyncMicroBatcher


class CrossEncoderReranker(UDF):
    """Jitted cross-encoder scoring (parity: rerankers.py CrossEncoderReranker)."""

    def __init__(
        self,
        model_name: str = "cross-encoder/ms-marco-MiniLM-L-6-v2",
        *,
        max_batch_size: int = 256,
        cache_strategy=None,
        **init_kwargs,
    ):
        super().__init__(executor=async_executor(), deterministic=True, cache_strategy=cache_strategy)
        from pathway_tpu.models import shared_cross_encoder

        self._ce = shared_cross_encoder(model_name)
        self._batcher = AsyncMicroBatcher(
            self._process,
            max_batch_size=max_batch_size,
            name=f"reranker:{model_name}",
        )

        async def rerank(doc: str, query: str) -> float:
            return await self._batcher.submit((query or "", _doc_text(doc)))

        self.__wrapped__ = rerank

    def _process(self, pairs: list[tuple[str, str]]) -> list[float]:
        return [float(s) for s in self._ce.score(pairs)]


class EncoderReranker(UDF):
    """Bi-encoder cosine rerank (parity: rerankers.py EncoderReranker)."""

    def __init__(self, embedder=None, model_name: str = "all-MiniLM-L6-v2", **kwargs):
        super().__init__(executor=async_executor(), deterministic=True)
        from pathway_tpu.models import shared_sentence_encoder

        self._enc = shared_sentence_encoder(model_name)
        self._batcher = AsyncMicroBatcher(self._process)

        async def rerank(doc: str, query: str) -> float:
            return await self._batcher.submit((query or "", _doc_text(doc)))

        self.__wrapped__ = rerank

    def _process(self, pairs: list[tuple[str, str]]) -> list[float]:
        texts = [t for pair in pairs for t in pair]
        vecs = self._enc.encode(texts)
        out = []
        for i in range(len(pairs)):
            q, d = vecs[2 * i], vecs[2 * i + 1]
            out.append(float(q @ d))
        return out


class LLMReranker(UDF):
    """Chat-based 1-5 relevance scoring (parity: rerankers.py LLMReranker)."""

    def __init__(self, llm, *, retry_strategy=None, cache_strategy=None, **kwargs):
        super().__init__(
            executor=async_executor(retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.llm = llm

        async def rerank(doc: str, query: str) -> float:
            prompt = (
                "Given a query and a document, rate on a scale from 1 to 5 how "
                "relevant the document is to the query. Respond with only the "
                f"number.\nQuery: {query}\nDocument: {_doc_text(doc)}\nScore:"
            )
            # keeps the LLM UDF's retry/capacity/cache config applied
            res = await self.llm.as_async_callable()(
                [{"role": "user", "content": prompt}]
            )
            m = re.search(r"[1-5]", str(res) or "")
            if not m:
                raise ValueError(f"reranker LLM returned no score: {res!r}")
            return float(m.group(0))

        self.__wrapped__ = rerank


class FlashRankReranker(UDF):
    """FlashRank reranker (parity: rerankers.py). Gated on `flashrank`."""

    def __init__(self, model: str = "ms-marco-TinyBERT-L-2-v2", **kwargs):
        super().__init__(executor=async_executor())
        self.model = model
        self._ranker = None

        async def rerank(doc: str, query: str) -> float:
            from flashrank import RerankRequest  # gated

            if self._ranker is None:
                from flashrank import Ranker

                self._ranker = Ranker(model_name=self.model)
            req = RerankRequest(query=query, passages=[{"text": _doc_text(doc)}])
            return float(self._ranker.rerank(req)[0]["score"])

        self.__wrapped__ = rerank


def _doc_text(doc: Any) -> str:
    if isinstance(doc, Json):
        doc = doc.value
    if isinstance(doc, dict):
        return str(doc.get("text", doc))
    return str(doc)


def rerank_topk_filter(
    docs: ColumnExpression, scores: ColumnExpression, k: int = 5
) -> ColumnExpression:
    """Keep the k best (docs, scores) pairs (parity: rerankers.py:58)."""

    def topk(docs_v, scores_v):
        order = np.argsort(-np.asarray(scores_v, dtype=float))[:k]
        return (
            tuple(docs_v[i] for i in order),
            tuple(float(scores_v[i]) for i in order),
        )

    return ApplyExpression(topk, None, docs, scores)
