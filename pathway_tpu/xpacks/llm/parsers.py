"""Document parsers (parity: xpacks/llm/parsers.py, 849 LoC).

``ParseUtf8`` (bytes→text), ``ParseUnstructured`` (gated on `unstructured`),
``ParseFromDocStore``-style identity.  Parsers are UDFs:
bytes → tuple[(text, metadata)].
"""

from __future__ import annotations

import json as _json
from typing import Any

from pathway_tpu.engine.types import Json
from pathway_tpu.internals.udfs import UDF


class ParseUtf8(UDF):
    """Decode bytes to one text document (parity: parsers.py ParseUtf8)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

        def parse(contents: bytes) -> tuple:
            if isinstance(contents, bytes):
                text = contents.decode("utf-8", errors="replace")
            else:
                text = str(contents)
            return ((text, Json({})),)

        self.__wrapped__ = parse


# reference alias
Utf8Parser = ParseUtf8


class ParseUnstructured(UDF):
    """unstructured-io parser (parity: parsers.py ParseUnstructured).
    Gated on the `unstructured` package."""

    def __init__(self, mode: str = "single", post_processors=None, **unstructured_kwargs):
        super().__init__()
        self.mode = mode
        self.kwargs = dict(unstructured_kwargs)

        def parse(contents: bytes) -> tuple:
            import io

            from unstructured.partition.auto import partition  # gated

            elements = partition(file=io.BytesIO(contents), **self.kwargs)
            if self.mode == "single":
                text = "\n\n".join(str(e) for e in elements)
                return ((text, Json({})),)
            out = []
            for e in elements:
                meta = e.metadata.to_dict() if hasattr(e, "metadata") else {}
                out.append((str(e), Json(meta)))
            return tuple(out)

        self.__wrapped__ = parse


UnstructuredParser = ParseUnstructured


class ParseJson(UDF):
    """Parse a JSON document into (text, metadata) using a text field."""

    def __init__(self, text_field: str = "text", **kwargs):
        super().__init__(**kwargs)

        def parse(contents: bytes) -> tuple:
            obj = _json.loads(contents.decode("utf-8", errors="replace") if isinstance(contents, bytes) else str(contents))
            text = obj.pop(text_field, "")
            return ((str(text), Json(obj)),)

        self.__wrapped__ = parse
