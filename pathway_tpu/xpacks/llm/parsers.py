"""Document parsers (parity: xpacks/llm/parsers.py, 849 LoC).

Parsers are UDFs: ``bytes → tuple[(text, metadata)]``.  The family mirrors
the reference's — ``Utf8Parser``, ``UnstructuredParser`` (chunking modes +
post-processors), ``PypdfParser``, ``DoclingParser``, ``ImageParser``,
``SlideParser`` — but the PDF/DOCX/PPTX text paths are self-contained
stdlib extractors (``_doc_extract``) because none of the reference's
parsing dependencies ship in this image.  ``unstructured``/``docling``
are used when importable, exactly like the reference gates them.
"""

from __future__ import annotations

import json as _json
from typing import Any, Callable, Iterable, Literal, get_args

from pathway_tpu.engine.types import Json
from pathway_tpu.internals.udfs import UDF
from pathway_tpu.xpacks.llm import _doc_extract

ChunkingMode = Literal["single", "elements", "paged", "basic", "by_title"]


def _apply_post_processors(
    text: str, post_processors: Iterable[Callable[[str], str]] | None
) -> str:
    for proc in post_processors or ():
        text = proc(text)
    return text


def chunk_elements(
    elements: list[tuple[str, dict]],
    mode: ChunkingMode,
    *,
    max_characters: int = 500,
    new_after_n_chars: int | None = None,
) -> list[tuple[str, dict]]:
    """Chunk (text, metadata) elements the way the reference's
    UnstructuredParser does (parsers.py:174-230): ``single`` joins all,
    ``elements`` keeps one doc per element, ``paged`` groups by
    ``page_number``, ``by_title`` starts a chunk at each Title element,
    ``basic`` packs elements into ≤``max_characters`` chunks (soft break
    at ``new_after_n_chars``).

    Example:

    >>> from pathway_tpu.xpacks.llm.parsers import chunk_elements
    >>> els = [
    ...     ("Intro", {"category": "Title", "page_number": 1}),
    ...     ("First paragraph.", {"page_number": 1}),
    ...     ("Methods", {"category": "Title", "page_number": 2}),
    ... ]
    >>> chunk_elements(els, "single")
    [('Intro\\n\\nFirst paragraph.\\n\\nMethods', {})]
    >>> [t for t, _m in chunk_elements(els, "by_title")]
    ['Intro\\nFirst paragraph.', 'Methods']
    >>> [m["page_number"] for _t, m in chunk_elements(els, "paged")]
    [1, 2]
    """
    if mode not in get_args(ChunkingMode):
        raise ValueError(
            f"Got {mode} for `chunking_mode`, but should be one of "
            f"`{get_args(ChunkingMode)}`"
        )
    if max_characters < 1:
        raise ValueError("`max_characters` must be a positive integer")
    if mode == "elements":
        return list(elements)
    if mode == "single":
        return [("\n\n".join(t for t, _m in elements), {})]
    if mode == "paged":
        pages: dict[Any, list[str]] = {}
        for text, meta in elements:
            pages.setdefault(meta.get("page_number"), []).append(text)
        return [
            ("\n".join(texts), {"page_number": page})
            for page, texts in sorted(
                pages.items(), key=lambda kv: (kv[0] is None, kv[0])
            )
        ]
    if mode == "by_title":
        chunks: list[list[tuple[str, dict]]] = []
        for text, meta in elements:
            if meta.get("category") == "Title" or not chunks:
                chunks.append([])
            chunks[-1].append((text, meta))
        return [
            ("\n".join(t for t, _m in chunk), dict(chunk[0][1]))
            for chunk in chunks
            if chunk
        ]
    # basic: pack into max_characters windows
    soft = new_after_n_chars or max_characters
    out: list[tuple[str, dict]] = []
    cur: list[str] = []
    cur_len = 0
    for text, _meta in elements:
        while len(text) > max_characters:  # oversized element: hard split
            if cur:
                out.append(("\n".join(cur), {}))
                cur, cur_len = [], 0
            out.append((text[:max_characters], {}))
            text = text[max_characters:]
        add = len(text) + (1 if cur else 0)
        if cur and (cur_len + add > max_characters or cur_len >= soft):
            out.append(("\n".join(cur), {}))
            cur, cur_len = [], 0
        cur.append(text)
        cur_len += add
    if cur:
        out.append(("\n".join(cur), {}))
    return out


class Utf8Parser(UDF):
    """Decode bytes to one text document (parity: parsers.py Utf8Parser)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

        def parse(contents: bytes) -> tuple:
            if isinstance(contents, bytes):
                text = contents.decode("utf-8", errors="replace")
            else:
                text = str(contents)
            return ((text, Json({})),)

        self.__wrapped__ = parse


# reference alias (deprecated name there)
ParseUtf8 = Utf8Parser


class UnstructuredParser(UDF):
    """unstructured-io parser with the reference's chunking modes and
    post-processors (parity: parsers.py UnstructuredParser:82-317).
    Gated on the ``unstructured`` package."""

    def __init__(
        self,
        chunking_mode: ChunkingMode = "single",
        post_processors: list[Callable[[str], str]] | None = None,
        chunking_kwargs: dict | None = None,
        mode: str | None = None,  # deprecated alias for chunking_mode
        **unstructured_kwargs,
    ):
        super().__init__()
        if mode is not None:
            chunking_mode = mode  # type: ignore[assignment]
        if chunking_mode not in get_args(ChunkingMode):
            raise ValueError(
                f"Got {chunking_mode} for `chunking_mode`, but should be "
                f"one of `{get_args(ChunkingMode)}`"
            )
        self.chunking_mode: ChunkingMode = chunking_mode
        self.post_processors = list(post_processors or [])
        self.chunking_kwargs = dict(chunking_kwargs or {})
        self.kwargs = dict(unstructured_kwargs)

        def parse(contents: bytes) -> tuple:
            import io

            from unstructured.partition.auto import partition  # gated

            elements = partition(file=io.BytesIO(contents), **self.kwargs)
            pairs = []
            for e in elements:
                meta = e.metadata.to_dict() if hasattr(e, "metadata") else {}
                if hasattr(e, "category"):
                    meta["category"] = e.category
                text = _apply_post_processors(str(e), self.post_processors)
                pairs.append((text, meta))
            chunks = chunk_elements(
                pairs, self.chunking_mode, **self.chunking_kwargs
            )
            return tuple((text, Json(meta)) for text, meta in chunks)

        self.__wrapped__ = parse


ParseUnstructured = UnstructuredParser


class ParseJson(UDF):
    """Parse a JSON document into (text, metadata) using a text field."""

    def __init__(self, text_field: str = "text", **kwargs):
        super().__init__(**kwargs)

        def parse(contents: bytes) -> tuple:
            obj = _json.loads(
                contents.decode("utf-8", errors="replace")
                if isinstance(contents, bytes)
                else str(contents)
            )
            text = obj.pop(text_field, "")
            return ((str(text), Json(obj)),)

        self.__wrapped__ = parse


class PypdfParser(UDF):
    """PDF → text (parity: parsers.py PypdfParser:775).

    Uses ``pypdf`` when importable; otherwise the stdlib extractor
    (``_doc_extract.pdf_extract_pages``) — FlateDecode content streams,
    text operators, page-tree page order.  ``chunking_mode``: ``single``
    (whole document) or ``paged`` (one doc per page with page_number).
    """

    def __init__(
        self,
        chunking_mode: Literal["single", "paged"] = "single",
        apply_text_cleanup: bool = True,
        post_processors: list[Callable[[str], str]] | None = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if chunking_mode not in ("single", "paged"):
            raise ValueError(
                f"Got {chunking_mode} for `chunking_mode`, "
                "but should be `single` or `paged`"
            )
        self.chunking_mode = chunking_mode
        self.apply_text_cleanup = apply_text_cleanup
        self.post_processors = list(post_processors or [])

        def parse(contents: bytes) -> tuple:
            pages = self._extract_pages(contents)
            if self.apply_text_cleanup:
                pages = [self._cleanup(p) for p in pages]
            pages = [
                _apply_post_processors(p, self.post_processors) for p in pages
            ]
            if self.chunking_mode == "paged":
                return tuple(
                    (text, Json({"page_number": i + 1}))
                    for i, text in enumerate(pages)
                )
            return (("\n\n".join(pages).strip(), Json({})),)

        self.__wrapped__ = parse

    @staticmethod
    def _extract_pages(contents: bytes) -> list[str]:
        try:
            import io

            from pypdf import PdfReader  # optional, like the reference

            reader = PdfReader(io.BytesIO(contents))
            return [page.extract_text() or "" for page in reader.pages]
        except ImportError:
            return _doc_extract.pdf_extract_pages(contents)

    @staticmethod
    def _cleanup(text: str) -> str:
        """Join hyphenated line breaks, collapse whitespace runs, drop
        empty lines (the reference's text cleanup switches)."""
        import re

        text = re.sub(r"-\n(\w)", r"\1", text)  # de-hyphenate across lines
        text = re.sub(r"[ \t]+", " ", text)
        lines = [ln.strip() for ln in text.splitlines()]
        return "\n".join(ln for ln in lines if ln)


class DocxParser(UDF):
    """DOCX → text via the stdlib WordprocessingML extractor."""

    def __init__(
        self,
        post_processors: list[Callable[[str], str]] | None = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.post_processors = list(post_processors or [])

        def parse(contents: bytes) -> tuple:
            text = _doc_extract.docx_extract_text(contents)
            text = _apply_post_processors(text, self.post_processors)
            return ((text, Json({})),)

        self.__wrapped__ = parse


class PptxParser(UDF):
    """PPTX → per-slide text via the stdlib PresentationML extractor.

    ``chunking_mode``: ``single`` (whole deck) or ``paged`` (one doc per
    slide, with ``slide_number`` metadata) — the text backbone of
    SlideParser/SlidesDocumentStore.
    """

    def __init__(
        self,
        chunking_mode: Literal["single", "paged"] = "paged",
        post_processors: list[Callable[[str], str]] | None = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if chunking_mode not in ("single", "paged"):
            raise ValueError(
                f"Got {chunking_mode} for `chunking_mode`, "
                "but should be `single` or `paged`"
            )
        self.chunking_mode = chunking_mode
        self.post_processors = list(post_processors or [])

        def parse(contents: bytes) -> tuple:
            slides = _doc_extract.pptx_extract_slides(contents)
            slides = [
                _apply_post_processors(s, self.post_processors) for s in slides
            ]
            if self.chunking_mode == "paged":
                return tuple(
                    (text, Json({"slide_number": i + 1}))
                    for i, text in enumerate(slides)
                )
            return (("\n\n".join(slides).strip(), Json({})),)

        self.__wrapped__ = parse


class ImageParser(UDF):
    """Image → description via a vision LLM (parity: parsers.py
    ImageParser:456).  Takes any chat UDF whose callable accepts an
    OpenAI-style message list (content parts with an ``image_url`` data
    URL)."""

    def __init__(
        self,
        llm: Any,
        parse_prompt: str = "Describe the image contents concisely.",
        downsize_horizontal_width: int | None = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.llm = llm
        self.parse_prompt = parse_prompt
        self.downsize_horizontal_width = downsize_horizontal_width

        def parse(contents: bytes) -> tuple:
            import base64

            data = contents
            if self.downsize_horizontal_width:
                data = _downsize_image(data, self.downsize_horizontal_width)
            b64 = base64.b64encode(data).decode()
            mime = _sniff_image_mime(data)
            messages = [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": self.parse_prompt},
                        {
                            "type": "image_url",
                            "image_url": {
                                "url": f"data:{mime};base64,{b64}"
                            },
                        },
                    ],
                }
            ]
            text = self.llm.__wrapped__(messages)
            return ((str(text), Json({})),)

        self.__wrapped__ = parse


def _sniff_image_mime(data: bytes) -> str:
    """Media type from magic bytes — vision APIs reject a mislabeled
    payload (e.g. a JPEG claiming image/png)."""
    if data[:8] == b"\x89PNG\r\n\x1a\n":
        return "image/png"
    if data[:2] == b"\xff\xd8":
        return "image/jpeg"
    if data[:6] in (b"GIF87a", b"GIF89a"):
        return "image/gif"
    if data[:4] == b"RIFF" and data[8:12] == b"WEBP":
        return "image/webp"
    return "image/png"


def _downsize_image(data: bytes, width: int) -> bytes:
    try:
        import io

        from PIL import Image

        img = Image.open(io.BytesIO(data))
        if img.width > width:
            img = img.resize((width, int(img.height * width / img.width)))
        out = io.BytesIO()
        img.save(out, format="PNG")
        return out.getvalue()
    except ImportError:
        return data


class SlideParser(UDF):
    """PPTX/PDF slides → text, optionally enriched by a vision LLM
    (parity: parsers.py SlideParser:598 — there each slide is rendered to
    an image for a vision model; here the text backbone is the stdlib
    extractor and the LLM enrichment is optional, since no slide
    rasterizer ships in this image)."""

    def __init__(
        self,
        llm: Any | None = None,
        parse_prompt: str = "Describe this slide concisely.",
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.llm = llm
        self.parse_prompt = parse_prompt

        def parse(contents: bytes) -> tuple:
            if contents[:4] == b"%PDF":
                texts = _doc_extract.pdf_extract_pages(contents)
                unit = "page_number"
            else:
                texts = _doc_extract.pptx_extract_slides(contents)
                unit = "slide_number"
            out = []
            for i, text in enumerate(texts):
                if self.llm is not None:
                    enriched = self.llm.__wrapped__(
                        [
                            {
                                "role": "user",
                                "content": f"{self.parse_prompt}\n\n{text}",
                            }
                        ]
                    )
                    text = str(enriched)
                out.append((text, Json({unit: i + 1})))
            return tuple(out)

        self.__wrapped__ = parse


class DoclingParser(UDF):
    """docling-based PDF→markdown parser (parity: parsers.py
    DoclingParser:329).  Gated on the ``docling`` package; falls back to
    the stdlib PDF extractor so the class stays usable in this image."""

    def __init__(self, chunk: bool = True, **kwargs):
        super().__init__()
        self.chunk = chunk
        self.kwargs = kwargs

        def parse(contents: bytes) -> tuple:
            try:
                return self._parse_docling(contents)
            except ImportError:
                pages = _doc_extract.pdf_extract_pages(contents)
                if self.chunk:
                    return tuple(
                        (text, Json({"page_number": i + 1}))
                        for i, text in enumerate(pages)
                    )
                return (("\n\n".join(pages).strip(), Json({})),)

        self.__wrapped__ = parse

    def _parse_docling(self, contents: bytes) -> tuple:
        import io

        from docling.document_converter import DocumentConverter  # gated

        converter = DocumentConverter(**self.kwargs)
        result = converter.convert(io.BytesIO(contents))
        md = result.document.export_to_markdown()
        return ((md, Json({})),)
