"""Shared helpers for the LLM xpack (parity: xpacks/llm/_utils.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.types import Json


def _coerce_sync(fn):
    import asyncio
    import functools

    if not asyncio.iscoroutinefunction(fn):
        return fn

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))

    return wrapper


def _extract_value(value: Any) -> Any:
    if isinstance(value, Json):
        return value.value
    return value


def _unwrap_udf(udf) -> Any:
    from pathway_tpu.internals.udfs import UDF

    if isinstance(udf, UDF):
        return udf.__wrapped__
    return udf
