"""Shared helpers for the LLM xpack (parity: xpacks/llm/_utils.py)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine.types import Json


def send_post_request(
    url: str, data: dict, headers: dict | None = None, timeout: int | None = None
):
    """POST JSON, raise on HTTP errors, return the parsed JSON response
    (parity: question_answering.py:870)."""
    import json as _json
    import urllib.request

    req = urllib.request.Request(
        url,
        data=_json.dumps(data).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return _json.loads(resp.read().decode())


def _coerce_sync(fn):
    import asyncio
    import functools

    if not asyncio.iscoroutinefunction(fn):
        return fn

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))

    return wrapper


def _extract_value(value: Any) -> Any:
    if isinstance(value, Json):
        return value.value
    return value


def _unwrap_udf(udf) -> Any:
    from pathway_tpu.internals.udfs import UDF

    if isinstance(udf, UDF):
        return udf.__wrapped__
    return udf
