"""REST servers for RAG apps (parity: xpacks/llm/servers.py:16-292).

``BaseRestServer``/``DocumentStoreServer``/``QARestServer``/
``QASummaryRestServer`` and ``serve_callable`` — all built on
``pw.io.http.rest_connector``: requests are streaming rows, responses are
delivered when the result row appears.

Generation-backed routes (``/v1/pw_ai_answer``, ``/v2/answer``,
``/v1/pw_ai_summary``) reach the decoder through the ``JaxChat`` UDF,
which routes through the process-wide continuous-batching scheduler
(``pathway_tpu/serving/generation.py``) — every route's requests share
ONE per-step-admitted device batch with paged KV, so a long answer on
one route never head-of-line-blocks a short one on another
(docs/generation_serving.md).
"""

from __future__ import annotations

import threading
from typing import Any, Callable

import pathway_tpu as pw
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io.http import PathwayWebserver, rest_connector


class BaseRestServer:
    def __init__(self, host: str, port: int, **rest_kwargs):
        self.host = host
        self.port = port
        self.webserver = PathwayWebserver(host=host, port=port)
        self._routes: list = []

    def serve(
        self,
        route: str,
        schema: type[schema_mod.Schema],
        handler: Callable[[Table], Table],
        *,
        methods: tuple = ("POST",),
        retry_strategy=None,
        cache_strategy=None,
        documentation=None,
        degraded_handler: Callable[[dict], Any] | None = None,
    ) -> None:
        """Mount ``handler`` on ``route``.

        ``degraded_handler`` is the overload fallback (engine/serving.py):
        while the admission controller's shedder is engaged, requests to
        this route are answered by the callable (sync or async,
        ``payload dict -> jsonable``) instead of the pipeline — e.g. a
        keyword-only retrieval when the embedding path is saturated.
        Responses carry ``X-Pathway-Degraded: 1``.  Routes without one
        shed with ``429`` instead."""
        queries, writer = rest_connector(
            webserver=self.webserver,
            route=route,
            methods=list(methods),
            schema=schema,
            autocommit_duration_ms=50,
            delete_completed_queries=False,
            documentation=documentation,
            degraded_handler=degraded_handler,
        )
        writer(handler(queries))
        self._routes.append(route)

    def run_server(
        self,
        threaded: bool = False,
        with_cache: bool = True,
        cache_backend: Any = None,
        terminate_on_error: bool = True,
        **kwargs,
    ):
        """Run the pipeline (parity: servers.py run_server).

        ``with_cache`` routes the UDF disk caches through the persistence
        layer, matching the reference's engine-persistence-backed DiskCache
        (udfs/caches.py:35, PersistenceMode::UdfCaching)."""
        persistence_config = None
        if with_cache:
            from pathway_tpu import persistence as _persistence

            backend = cache_backend or _persistence.Backend.filesystem(
                "./Cache"
            )
            # UDF-cache-only: input snapshotting stays off, so restarting the
            # server does not replay old HTTP query rows
            persistence_config = _persistence.Config(
                backend, persistence_mode=pw.PersistenceMode.UDF_CACHING
            )

        def _run():
            return pw.run(
                terminate_on_error=terminate_on_error,
                persistence_config=persistence_config,
            )

        if threaded:
            t = threading.Thread(target=_run, daemon=True, name="pathway:server")
            t.start()
            return t
        return _run()


class DocumentStoreServer(BaseRestServer):
    """Exposes /v1/retrieve, /v1/statistics, /v1/inputs (parity :16)."""

    def __init__(self, host: str, port: int, document_store, **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.document_store = document_store
        self.serve(
            "/v1/retrieve",
            document_store.RetrieveQuerySchema,
            document_store.retrieve_query,
            methods=("GET", "POST"),
        )
        self.serve(
            "/v1/statistics",
            document_store.StatisticsQuerySchema,
            document_store.statistics_query,
            methods=("GET", "POST"),
        )
        self.serve(
            "/v1/inputs",
            document_store.InputsQuerySchema,
            document_store.inputs_query,
            methods=("GET", "POST"),
        )


class QARestServer(BaseRestServer):
    """Exposes the question-answerer endpoints (parity: servers.py:~150)."""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, **rest_kwargs)
        self.rag = rag_question_answerer
        self.serve(
            "/v1/pw_ai_answer",
            rag_question_answerer.AnswerQuerySchema,
            rag_question_answerer.answer_query,
            methods=("POST",),
        )
        self.serve(
            "/v2/answer",
            rag_question_answerer.AnswerQuerySchema,
            rag_question_answerer.answer_query,
            methods=("POST",),
        )
        self.serve(
            "/v1/retrieve",
            rag_question_answerer.RetrieveQuerySchema,
            rag_question_answerer.retrieve,
            methods=("GET", "POST"),
        )
        self.serve(
            "/v1/pw_list_documents",
            rag_question_answerer.InputsQuerySchema,
            rag_question_answerer.list_documents,
            methods=("GET", "POST"),
        )
        self.serve(
            "/v2/list_documents",
            rag_question_answerer.InputsQuerySchema,
            rag_question_answerer.list_documents,
            methods=("GET", "POST"),
        )
        self.serve(
            "/v1/statistics",
            rag_question_answerer.StatisticsQuerySchema,
            rag_question_answerer.statistics,
            methods=("GET", "POST"),
        )


class QASummaryRestServer(QARestServer):
    """Adds the summarization endpoint (parity: servers.py:~250)."""

    def __init__(self, host: str, port: int, rag_question_answerer, **rest_kwargs):
        super().__init__(host, port, rag_question_answerer, **rest_kwargs)
        self.serve(
            "/v1/pw_ai_summary",
            rag_question_answerer.SummarizeQuerySchema,
            rag_question_answerer.summarize_query,
            methods=("POST",),
        )
        self.serve(
            "/v2/summarize",
            rag_question_answerer.SummarizeQuerySchema,
            rag_question_answerer.summarize_query,
            methods=("POST",),
        )


def serve_callable(
    route: str,
    schema: type[schema_mod.Schema],
    host: str,
    port: int,
    callable_func: Callable | None = None,
    **kwargs,
):
    """Serve a Python callable as a REST endpoint over the streaming engine
    (parity: servers.py serve_callable decorator)."""

    def decorator(func: Callable):
        server = BaseRestServer(host, port)

        def handler(queries: Table) -> Table:
            cols = [getattr(pw.this, n) for n in schema.column_names()]
            return queries.select(
                result=pw.apply_with_type(
                    lambda *vals: func(**dict(zip(schema.column_names(), vals))),
                    object,
                    *cols,
                )
            )

        server.serve(route, schema, handler, **kwargs)
        func._pw_server = server  # type: ignore[attr-defined]
        return func

    if callable_func is not None:
        return decorator(callable_func)
    return decorator
