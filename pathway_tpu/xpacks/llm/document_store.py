"""DocumentStore — parse → post-process → split → index pipeline
(parity: xpacks/llm/document_store.py:32-498).

Inputs: tables of (data: bytes, _metadata: Json) from any connector.
Queries (retrieve/statistics/inputs) are streaming tables; answers are
as-of-now index lookups (§3.4 of SURVEY.md).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import pathway_tpu as pw
from pathway_tpu.engine.types import Json
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.indexing.retrievers import AbstractRetrieverFactory
from pathway_tpu.xpacks.llm.parsers import ParseUtf8
from pathway_tpu.xpacks.llm.splitters import NullSplitter


class DocumentStore:
    """Builds and serves a document index over streaming input tables."""

    class RetrieveQuerySchema(pw.Schema):
        query: str
        k: int
        metadata_filter: str | None
        filepath_globpattern: str | None

    class StatisticsQuerySchema(pw.Schema):
        pass

    class InputsQuerySchema(pw.Schema):
        metadata_filter: str | None
        filepath_globpattern: str | None

    def __init__(
        self,
        docs: Table | Iterable[Table],
        retriever_factory: AbstractRetrieverFactory,
        parser: Any | None = None,
        splitter: Any | None = None,
        doc_post_processors: list[Callable[[str, Json], tuple[str, Json]]] | None = None,
    ):
        if isinstance(docs, Table):
            docs_tables = [docs]
        else:
            docs_tables = list(docs)
        if not docs_tables:
            raise ValueError(
                "DocumentStore requires at least one documents table "
                "(got an empty `docs`); pass e.g. pw.io.fs.read(...)"
            )
        self.docs = (
            docs_tables[0].concat_reindex(*docs_tables[1:])
            if len(docs_tables) > 1
            else docs_tables[0]
        )
        self.retriever_factory = retriever_factory
        self.parser = parser or ParseUtf8()
        self.splitter = splitter or NullSplitter()
        self.doc_post_processors = doc_post_processors or []
        self._build()

    def _build(self) -> None:
        docs = self.docs
        has_meta = "_metadata" in docs.column_names()
        if not has_meta:
            docs = docs.with_columns(_metadata=expr_mod.ColumnConstExpression(Json({})))

        # 1. parse: data -> tuple[(text, meta)]
        parsed = docs.with_columns(
            _pw_parsed=self.parser(ColumnReference(this, "data"))
        )
        parsed_flat = parsed.flatten(
            ColumnReference(this, "_pw_parsed"), origin_id="_pw_doc_id"
        )
        parsed_docs = parsed_flat.select(
            text=ApplyExpression(lambda p: p[0], str, ColumnReference(this, "_pw_parsed")),
            metadata=ApplyExpression(
                _merge_meta, None, ColumnReference(this, "_pw_parsed"),
                ColumnReference(this, "_metadata"),
            ),
        )

        # 2. post-process
        for post in self.doc_post_processors:
            parsed_docs = parsed_docs.select(
                _pw_pp=ApplyExpression(
                    lambda t, m, _p=post: tuple(_p(t, m)),
                    None,
                    ColumnReference(this, "text"),
                    ColumnReference(this, "metadata"),
                )
            ).select(
                text=ApplyExpression(lambda p: p[0], str, ColumnReference(this, "_pw_pp")),
                metadata=ApplyExpression(lambda p: p[1], None, ColumnReference(this, "_pw_pp")),
            )
        self.parsed_docs = parsed_docs

        # 3. split: text -> tuple[(chunk, meta)]
        chunked = parsed_docs.with_columns(
            _pw_chunks=self.splitter(
                ColumnReference(this, "text"), ColumnReference(this, "metadata")
            )
        )
        chunks_flat = chunked.flatten(
            ColumnReference(this, "_pw_chunks"), origin_id="_pw_parent"
        )
        self.chunked_docs = chunks_flat.select(
            text=ApplyExpression(lambda c: c[0], str, ColumnReference(this, "_pw_chunks")),
            metadata=ApplyExpression(
                _merge_chunk_meta,
                None,
                ColumnReference(this, "_pw_chunks"),
                ColumnReference(this, "metadata"),
            ),
        )

        # 4. index
        self._index = self.retriever_factory.build_index(
            ColumnReference(self.chunked_docs, "text"),
            self.chunked_docs,
            metadata_column=ColumnReference(self.chunked_docs, "metadata"),
        )

    @property
    def index(self):
        return self._index

    @staticmethod
    def merge_filters(queries: Table) -> Table:
        """Merge metadata_filter and filepath_globpattern into one filter
        expression (parity: document_store.py merge_filters)."""

        def merge(metadata_filter, globpattern):
            clauses = []
            if metadata_filter:
                clauses.append(f"({metadata_filter})")
            if globpattern:
                clauses.append(f"globmatch('{globpattern}', path)")
            return " && ".join(clauses) if clauses else None

        return queries.with_columns(
            metadata_filter=ApplyExpression(
                merge,
                None,
                ColumnReference(this, "metadata_filter"),
                ColumnReference(this, "filepath_globpattern"),
                _propagate_none=False,
            )
        )

    def retrieve_query(self, retrieval_queries: Table) -> Table:
        """queries(query, k, metadata_filter, filepath_globpattern) → result."""
        queries = self.merge_filters(retrieval_queries)
        matched = self._index.query_as_of_now(
            ColumnReference(queries, "query"),
            number_of_matches=ColumnReference(queries, "k"),
            metadata_filter=ColumnReference(queries, "metadata_filter"),
            collapse_rows=True,
        )

        def pack(texts, metas, scores) -> Json:
            out = []
            for t, m, s in zip(texts or (), metas or (), scores or ()):
                out.append(
                    {
                        "text": t,
                        "metadata": m.value if isinstance(m, Json) else m,
                        "dist": -float(s),
                    }
                )
            return Json(out)

        return matched.select(
            result=ApplyExpression(
                pack,
                None,
                ColumnReference(this, "text"),
                ColumnReference(this, "metadata"),
                ColumnReference(this, "_pw_index_reply_score"),
                _propagate_none=False,
            )
        )

    def statistics_query(self, info_queries: Table) -> Table:
        """Document-count / last-modified statistics (parity :498)."""
        stats = self.parsed_docs.reduce(
            count=reducers.count(),
            last_modified=ApplyExpression(
                lambda ts: ts[-1] if ts else None,
                None,
                reducers.sorted_tuple(
                    ApplyExpression(
                        _modified_at, None, ColumnReference(this, "metadata")
                    ),
                    skip_nones=True,
                ),
                _propagate_none=False,
            ),
        )

        def pack(count, last_modified) -> Json:
            return Json(
                {
                    "file_count": count if count is not None else 0,
                    "last_modified": last_modified,
                    "last_indexed": last_modified,
                }
            )

        stats_view = stats
        return info_queries.select(
            result=ApplyExpression(
                pack,
                None,
                expr_mod.coalesce(_global_scalar(info_queries, stats_view, "count"), 0),
                _global_scalar(info_queries, stats_view, "last_modified"),
                _propagate_none=False,
            )
        )

    def inputs_query(self, input_queries: Table) -> Table:
        """List indexed input files, honoring the query's ``metadata_filter``
        and ``filepath_globpattern`` (parity: document_store.py inputs, which
        applies merged filters per query)."""
        import fnmatch

        from pathway_tpu.stdlib.indexing.filters import metadata_matches

        files = self.parsed_docs.reduce(
            paths=reducers.tuple(
                ApplyExpression(_meta_path_entry, None, ColumnReference(this, "metadata"))
            )
        )

        def pack(paths, metadata_filter, globpattern) -> Json:
            out = []
            for p in paths or ():
                if p is None:
                    continue
                entry = p.value if isinstance(p, Json) else p
                path = entry.get("path") if isinstance(entry, dict) else None
                if globpattern and not fnmatch.fnmatch(str(path or ""), globpattern):
                    continue
                if metadata_filter and not metadata_matches(metadata_filter, entry):
                    continue
                out.append(entry)
            return Json(out)

        return input_queries.select(
            result=ApplyExpression(
                pack,
                None,
                _global_scalar(input_queries, files, "paths"),
                ColumnReference(this, "metadata_filter"),
                ColumnReference(this, "filepath_globpattern"),
                _propagate_none=False,
            )
        )


class SlidesDocumentStore(DocumentStore):
    """Document store for the slide-search application (parity:
    document_store.py:471-529): a DocumentStore whose default parser is
    the slide parser, plus a ``parsed_documents_query`` returning the
    per-slide metadata after parsing/post-processing (with the bulky
    ``b64_image`` entries stripped, like the reference)."""

    excluded_response_metadata = ["b64_image"]

    def __init__(self, docs, retriever_factory, parser=None, **kwargs):
        if parser is None:
            from pathway_tpu.xpacks.llm.parsers import SlideParser

            parser = SlideParser()
        super().__init__(docs, retriever_factory, parser=parser, **kwargs)

    def parsed_documents_query(self, parse_docs_queries: Table) -> Table:
        """List parsed documents' metadata, honoring ``metadata_filter``
        (InputsQuerySchema queries)."""
        from pathway_tpu.stdlib.indexing.filters import metadata_matches

        metas = self.parsed_docs.reduce(
            metadatas=reducers.tuple(ColumnReference(this, "metadata"))
        )
        merged = self.merge_filters(parse_docs_queries)

        def pack(metadatas, metadata_filter) -> Json:
            out = []
            for m in metadatas or ():
                entry = dict(m.value) if isinstance(m, Json) else dict(m or {})
                if metadata_filter and not metadata_matches(
                    metadata_filter, entry
                ):
                    continue
                for key in self.excluded_response_metadata:
                    entry.pop(key, None)
                out.append(entry)
            return Json(out)

        return merged.select(
            result=ApplyExpression(
                pack,
                None,
                _global_scalar(merged, metas, "metadatas"),
                ColumnReference(this, "metadata_filter"),
                _propagate_none=False,
            )
        )


def _merge_meta(parsed_pair, file_meta):
    meta = parsed_pair[1]
    m = dict(meta.value) if isinstance(meta, Json) else dict(meta or {})
    if isinstance(file_meta, Json) and isinstance(file_meta.value, dict):
        m = {**file_meta.value, **m}
    return Json(m)


def _merge_chunk_meta(chunk_pair, parent_meta):
    meta = chunk_pair[1]
    m = dict(meta.value) if isinstance(meta, Json) else dict(meta or {})
    if isinstance(parent_meta, Json) and isinstance(parent_meta.value, dict):
        m = {**parent_meta.value, **m}
    return Json(m)


def _modified_at(meta):
    if isinstance(meta, Json) and isinstance(meta.value, dict):
        return meta.value.get("modified_at")
    return None


def _meta_path_entry(meta):
    # returns Json (hashable) — reducer args must be hashable engine values
    if isinstance(meta, Json) and isinstance(meta.value, dict):
        m = meta.value
        return Json(
            {
                "path": m.get("path"),
                "size": m.get("size"),
                "modified_at": m.get("modified_at"),
            }
        )
    return None


def _global_scalar(query_table: Table, scalar_table: Table, column: str):
    """Reference a single-row aggregate from every query row: the aggregate
    is re-keyed by a constant, and each query row ix-fetches that constant
    pointer — incremental and key-agnostic."""
    keyed = scalar_table.with_columns(_pw_one=expr_mod.ColumnConstExpression(0)).with_id_from(
        ColumnReference(this, "_pw_one")
    )
    view = keyed.ix(
        expr_mod.PointerExpression(keyed, expr_mod.ColumnConstExpression(0)),
        optional=True,
    )
    return getattr(view, column)
