"""Embedders (parity: xpacks/llm/embedders.py:85-401).

``SentenceTransformerEmbedder`` is the TPU-native path: a jit-compiled Flax
bi-encoder behind an async micro-batcher, so every concurrently-streaming
row of an epoch lands in one padded device batch (the north-star bridge).
API-based embedders (OpenAI/LiteLLM/Gemini) keep reference parity and are
gated on their client packages.
"""

from __future__ import annotations

import asyncio
from typing import Any

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression
from pathway_tpu.internals.udfs import UDF, async_executor
from pathway_tpu.utils.batching import AsyncMicroBatcher


class BaseEmbedder(UDF):
    def get_embedding_dimension(self, **kwargs) -> int:
        """Embed a probe string and measure (reference embedders.py)."""
        result = self.__wrapped__("pathway_tpu probe")
        if asyncio.iscoroutine(result):
            result = asyncio.run(result)
        return len(result)

    def __call__(self, input: ColumnExpression | Any = None, **kwargs) -> ColumnExpression:
        if input is None:
            raise TypeError("embedder requires an input expression")
        return super().__call__(input, **kwargs)


class SentenceTransformerEmbedder(BaseEmbedder):
    """Device-native analog of the reference's SentenceTransformer wrapper
    (embedders.py:~301): same constructor surface, but ``model`` resolves to
    a jitted Flax encoder rather than a torch module.
    """

    def __init__(
        self,
        model: str = "all-MiniLM-L6-v2",
        call_kwargs: dict = {},
        device: str = "auto",
        *,
        max_batch_size: int = 256,
        mesh=None,
        **init_kwargs,
    ):
        super().__init__(executor=async_executor(), deterministic=True)
        self.model_name = model
        if mesh is not None:
            # long-context mode: the sequence axis shards over the mesh
            # (ring attention), so documents far beyond the model's
            # max_len embed without truncation
            from pathway_tpu.models.long_context import (
                shared_long_context_encoder,
            )

            self._encoder = shared_long_context_encoder(model, mesh)
        else:
            from pathway_tpu.models import shared_sentence_encoder

            self._encoder = shared_sentence_encoder(model)
        self._batcher = AsyncMicroBatcher(
            self._process_batch,
            max_batch_size=max_batch_size,
            name=f"embedder:{model}",
        )

        async def embed(text: str) -> np.ndarray:
            return await self._batcher.submit(text if text is not None else "")

        embed.__name__ = f"sentence_transformer:{model}"
        self.__wrapped__ = embed

    def _process_batch(self, texts: list[str]) -> list[np.ndarray]:
        vectors = self._encoder.encode(texts)
        return [vectors[i] for i in range(len(texts))]

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._encoder.dimensions


# TPU-native default; the reference aliases its default embedder similarly
SentenceTransformerTask = SentenceTransformerEmbedder


class OpenAIEmbedder(BaseEmbedder):
    """OpenAI API embedder (parity: embedders.py:85). Gated on `openai`."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = "text-embedding-3-small",
        retry_strategy=None,
        cache_strategy=None,
        **openai_kwargs,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(openai_kwargs)

        async def embed(input: str, **kwargs) -> np.ndarray:
            import openai  # gated

            client = openai.AsyncOpenAI()
            params = {**self.kwargs, **kwargs, "model": self.model}
            ret = await client.embeddings.create(input=[input or "."], **params)
            return np.array(ret.data[0].embedding)

        self.__wrapped__ = embed


class LiteLLMEmbedder(BaseEmbedder):
    """LiteLLM embedder (parity: embedders.py). Gated on `litellm`."""

    def __init__(
        self,
        capacity: int | None = None,
        model: str | None = None,
        retry_strategy=None,
        cache_strategy=None,
        **llmlite_kwargs,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(llmlite_kwargs)

        async def embed(input: str, **kwargs) -> np.ndarray:
            import litellm  # gated

            ret = await litellm.aembedding(
                input=[input or "."], model=self.model, **{**self.kwargs, **kwargs}
            )
            return np.array(ret.data[0]["embedding"])

        self.__wrapped__ = embed


class GeminiEmbedder(BaseEmbedder):
    """Gemini embedder (parity: embedders.py:~401). Gated on google client."""

    def __init__(
        self,
        model: str | None = "models/embedding-001",
        capacity: int | None = None,
        retry_strategy=None,
        cache_strategy=None,
        **gemini_kwargs,
    ):
        super().__init__(
            executor=async_executor(capacity=capacity, retry_strategy=retry_strategy),
            cache_strategy=cache_strategy,
        )
        self.model = model
        self.kwargs = dict(gemini_kwargs)

        async def embed(input: str, **kwargs) -> np.ndarray:
            import google.generativeai as genai  # gated

            ret = genai.embed_content(
                model=self.model, content=input or ".", **{**self.kwargs, **kwargs}
            )
            return np.array(ret["embedding"])

        self.__wrapped__ = embed


class MultimodalEmbedder(BaseEmbedder):
    """SigLIP-class image+text embedder into one shared space.

    Beyond-reference capability named by BASELINE.md's multimodal RAG
    config (the reference's embedders are text-only API/torch wrappers,
    ``xpacks/llm/embedders.py:85-401``).  Both towers are jitted JAX
    programs (``models/vision.py``); text rows and image rows land in the
    same ``proj_dim`` space, so one ``DocumentStore``/sharded index serves
    a mixed corpus.

    Accepted inputs per row: ``str`` (text), ``np.ndarray`` (HWC image),
    or ``bytes`` — a ``.npy`` serialization, or any image format Pillow
    can open when Pillow is importable.
    """

    def __init__(
        self,
        model: str = "siglip-base-patch16-224",
        *,
        max_batch_size: int = 64,
        **init_kwargs,
    ):
        super().__init__(executor=async_executor(), deterministic=True)
        from pathway_tpu.models.vision import shared_multimodal_encoder

        self.model_name = model
        self._encoder = shared_multimodal_encoder(model)
        from pathway_tpu.device import stack_rows

        self._text_batcher = AsyncMicroBatcher(
            lambda texts: list(self._encoder.embed_texts(texts)),
            max_batch_size=max_batch_size,
            name=f"embedder:{model}:text",
        )
        # stack_rows (not np.stack): a dtype/shape mix in one coalesced
        # image batch fails loudly instead of silently upcasting
        self._image_batcher = AsyncMicroBatcher(
            lambda imgs: list(self._encoder.embed_images(stack_rows(imgs)[0])),
            max_batch_size=max_batch_size,
            name=f"embedder:{model}:image",
        )

        async def embed(input: Any = None, **kwargs) -> np.ndarray:
            img = _decode_image(input, self._encoder.vision_config.image_size)
            if img is not None:
                return await self._image_batcher.submit(img)
            return await self._text_batcher.submit(
                input if isinstance(input, str) else str(input or "")
            )

        embed.__name__ = f"multimodal:{model}"
        self.__wrapped__ = embed

    def get_embedding_dimension(self, **kwargs) -> int:
        return self._encoder.dimensions


def _decode_image(value: Any, image_size: int) -> np.ndarray | None:
    """Best-effort decode of a row value into a ``[S, S, 3]`` f32 image;
    returns None for text rows.  Pre-resizes so ragged sources stack into
    one device batch."""
    from pathway_tpu.models.vision import _resize_bilinear

    arr = None
    if isinstance(value, np.ndarray) and value.ndim >= 2:
        arr = value
    elif isinstance(value, bytes):
        import io

        try:
            loaded = np.load(io.BytesIO(value), allow_pickle=False)
            if isinstance(loaded, np.ndarray) and loaded.ndim >= 2:
                arr = loaded
        except Exception:
            try:
                from PIL import Image  # gated: Pillow is optional

                arr = np.asarray(Image.open(io.BytesIO(value)).convert("RGB"))
            except Exception:
                return None
    if arr is None:
        return None
    arr = np.asarray(arr)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.ndim != 3:
        return None
    # CHW layouts (channel-count leading, spatial dims trailing) → HWC
    if arr.shape[0] in (1, 3, 4) and arr.shape[-1] not in (1, 2, 3, 4):
        arr = arr.transpose(1, 2, 0)
    c = arr.shape[-1]
    if c == 1:
        arr = np.repeat(arr, 3, axis=2)
    elif c == 2:  # e.g. gray+alpha: keep luminance, drop alpha
        arr = np.repeat(arr[..., :1], 3, axis=2)
    elif c > 3:
        arr = arr[..., :3]
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    # keep [0, 1] floats: embed_images applies the [-1, 1] mapping once
    arr = arr.astype(np.float32)
    if arr.shape[0] != image_size or arr.shape[1] != image_size:
        arr = _resize_bilinear(arr[None, ...], image_size)[0]
    return arr
