"""Mock components for testing (parity: xpacks/llm/tests/mocks.py:5-25).

Mock the *components*, not the engine — pipelines exercise the real
dataflow/index path with deterministic fakes.
"""

from __future__ import annotations

import hashlib

import numpy as np

from pathway_tpu.internals.udfs import UDF


class FakeChatModel(UDF):
    """Always answers 'Text' (reference FakeChatModel)."""

    def __init__(self):
        super().__init__()

        def chat(messages, **kwargs) -> str:
            return "Text"

        self.__wrapped__ = chat

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return True


class IdentityMockChat(UDF):
    """Echoes 'model: last message content'."""

    def __init__(self):
        super().__init__()

        def chat(messages, model="mock", **kwargs) -> str:
            from pathway_tpu.engine.types import Json

            if isinstance(messages, Json):
                messages = messages.value
            if isinstance(messages, str):
                content = messages
            else:
                content = messages[-1].get("content", "") if messages else ""
            return f"{model}: {content}"

        self.__wrapped__ = chat


def fake_embeddings_model_fn(text: str) -> np.ndarray:
    """Deterministic 8-dim embedding from a text hash (reference
    fake_embeddings_model)."""
    h = hashlib.blake2b((text or "").encode(), digest_size=16).digest()
    v = np.frombuffer(h, dtype=np.uint8).astype(np.float32)[:8]
    n = np.linalg.norm(v) + 1e-9
    return v / n


class FakeEmbeddings(UDF):
    def __init__(self, dims: int = 8):
        super().__init__(deterministic=True)
        self.dims = dims

        def embed(text: str) -> np.ndarray:
            return fake_embeddings_model_fn(text)

        self.__wrapped__ = embed

    def get_embedding_dimension(self, **kwargs) -> int:
        return self.dims


fake_embeddings_model = FakeEmbeddings()
