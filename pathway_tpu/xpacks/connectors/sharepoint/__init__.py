"""Microsoft SharePoint reader (enterprise-gated).

Parity target: ``python/pathway/xpacks/connectors/sharepoint/__init__.py``
— certificate-authenticated site access via the ``office365`` client,
polling a directory tree on ``refresh_interval``, emitting one binary
``data`` row per file (plus ``_metadata`` when requested) with
upsert/delete semantics on modification, gated on the
``XPACK-SHAREPOINT`` license entitlement.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.config import get_config
from pathway_tpu.internals.license import License
from pathway_tpu.internals.table import Table
from pathway_tpu.io import python as io_python

logger = logging.getLogger("pathway_tpu.xpacks.sharepoint")

STATUS_DOWNLOADED = "downloaded"
STATUS_SIZE_LIMIT_EXCEEDED = "size_limit_exceeded"


def _check_entitled() -> None:
    License.new(get_config().license_key).check_entitlements(["xpack-sharepoint"])


class _SharePointSubject(io_python.ConnectorSubject):
    """Polls the site tree and streams file snapshots as upserts."""

    def __init__(
        self,
        *,
        url: str,
        tenant: str,
        client_id: str,
        cert_path: str,
        thumbprint: str,
        root_path: str,
        mode: str,
        recursive: bool,
        object_size_limit: int | None,
        with_metadata: bool,
        refresh_interval: int,
        max_failed_attempts_in_row: int | None,
    ):
        super().__init__(datasource_name="sharepoint")
        self.url = url
        self.auth = dict(
            tenant=tenant,
            client_id=client_id,
            cert_path=cert_path,
            thumbprint=thumbprint,
        )
        self.root_path = root_path
        self.mode = mode
        self.recursive = recursive
        self.object_size_limit = object_size_limit
        self.with_metadata = with_metadata
        self.refresh_interval = refresh_interval
        self.max_failed_attempts_in_row = max_failed_attempts_in_row
        self._seen: dict[str, int] = {}  # path -> modified_at

    def _context(self):
        from office365.sharepoint.client_context import ClientContext

        return ClientContext(self.url).with_client_certificate(
            tenant=self.auth["tenant"],
            client_id=self.auth["client_id"],
            cert_path=self.auth["cert_path"],
            thumbprint=self.auth["thumbprint"],
        )

    def _walk(self, ctx, path: str):
        folder = ctx.web.get_folder_by_server_relative_path(path)
        ctx.load(folder.files).execute_query()
        for entry in folder.files:
            yield entry
        if self.recursive:
            ctx.load(folder.folders).execute_query()
            for sub in folder.folders:
                yield from self._walk(ctx, sub.properties["ServerRelativeUrl"])

    def _scan_once(self, ctx) -> None:
        for entry in self._walk(ctx, self.root_path):
            path = entry.properties["ServerRelativeUrl"]
            modified = int(entry.time_last_modified.timestamp())
            if self._seen.get(path) == modified:
                continue
            size = entry.length
            status = STATUS_DOWNLOADED
            if self.object_size_limit is not None and size > self.object_size_limit:
                status = STATUS_SIZE_LIMIT_EXCEEDED
                payload = b""
            else:
                payload = entry.read()
            self._seen[path] = modified
            row: dict[str, Any] = {"data": payload, "_pw_key": path}
            if self.with_metadata:
                row["_metadata"] = json.dumps(
                    {
                        "path": path,
                        "size": size,
                        "modified_at": modified,
                        "created_at": int(entry.time_created.timestamp()),
                        "seen_at": int(time.time()),
                        "status": status,
                    }
                )
            self.next(**row)

    def run(self) -> None:
        failures = 0
        while True:
            try:
                self._scan_once(self._context())
                failures = 0
            except Exception as exc:
                failures += 1
                logger.warning("sharepoint scan failed (%d in row): %s", failures, exc)
                if (
                    self.max_failed_attempts_in_row is not None
                    and failures >= self.max_failed_attempts_in_row
                ):
                    raise
            self.commit()
            if self.mode == "static":
                break
            time.sleep(self.refresh_interval)
        self.close()


def read(
    url: str,
    *,
    tenant: str,
    client_id: str,
    cert_path: str,
    thumbprint: str,
    root_path: str,
    mode: str = "streaming",
    recursive: bool = True,
    object_size_limit: int | None = None,
    with_metadata: bool = False,
    refresh_interval: int = 30,
    max_failed_attempts_in_row: int | None = 8,
) -> Table:
    """Read a SharePoint directory/file as a binary ``data`` table.

    Requires the XPACK-SHAREPOINT license entitlement and the optional
    ``office365`` client package (reference gates identically via
    ``optional_imports("xpack-sharepoint")``).
    """
    _check_entitled()
    try:
        import office365  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "pw.xpacks.connectors.sharepoint.read requires the 'office365' "
            "package, which is not installed in this environment"
        ) from exc
    cols = {"data": schema_mod.ColumnSchema(name="data", dtype=schema_mod.dt.BYTES)}
    if with_metadata:
        cols["_metadata"] = schema_mod.ColumnSchema(
            name="_metadata", dtype=schema_mod.dt.JSON
        )
    schema = schema_mod.schema_from_columns(cols, name="SharePointSchema")
    subject = _SharePointSubject(
        url=url,
        tenant=tenant,
        client_id=client_id,
        cert_path=cert_path,
        thumbprint=thumbprint,
        root_path=root_path,
        mode=mode,
        recursive=recursive,
        object_size_limit=object_size_limit,
        with_metadata=with_metadata,
        refresh_interval=refresh_interval,
        max_failed_attempts_in_row=max_failed_attempts_in_row,
    )
    return io_python.read(subject, schema=schema)
