"""Process-orchestration CLI.

Parity target: ``python/pathway/cli.py`` — ``spawn`` forks N identical
processes of the user's script with ``PATHWAY_THREADS/PROCESSES/
FIRST_PORT/PROCESS_ID/RUN_ID`` set (every worker builds the same dataflow
and owns a shard, SURVEY.md §2b); ``replay`` re-runs a script against a
recorded input stream; ``spawn-from-env`` re-execs ``spawn`` with
arguments taken from ``PATHWAY_SPAWN_ARGS`` (the k8s-operator hook).

TPU mapping: one spawned process per TPU host (the reference maps one per
CPU socket); in-process workers become mesh axes, so ``--threads`` is
accepted for parity but the device mesh is what actually scales compute.
"""

from __future__ import annotations

import os
import secrets
import subprocess
import sys
import uuid
from typing import Any, NoReturn

import click

import pathway_tpu as pw


def _cluster_env(
    env_base: dict[str, str],
    *,
    threads: int,
    processes: int,
    first_port: int,
    process_id: int,
    run_id: str,
) -> dict[str, str]:
    env = dict(env_base)
    env.update(
        PATHWAY_THREADS=str(threads),
        PATHWAY_PROCESSES=str(processes),
        PATHWAY_FIRST_PORT=str(first_port),
        PATHWAY_PROCESS_ID=str(process_id),
        PATHWAY_RUN_ID=run_id,
    )
    return env


def spawn_program(
    *,
    threads: int,
    processes: int,
    first_port: int,
    program: str,
    arguments: tuple[str, ...],
    env_base: dict[str, str],
    supervise: bool = False,
    max_restarts: int = 3,
    checkpoint_root: str | None = None,
    shrink_on_loss: bool | None = None,
    autoscale: bool | None = None,
    standbys: int | None = None,
) -> NoReturn:
    """Launch ``processes`` copies of ``program`` forming one SPMD cluster.

    With ``supervise=True`` a crashed worker does not end the run: the
    supervisor (``engine/supervisor.py``) rolls the whole group back to
    the last committed persistence checkpoint and respawns it, up to
    ``max_restarts`` times — same run id, ports and comm secret, so the
    recovered cluster resumes exactly where the snapshots left off.

    With ``standbys=K`` (or ``PATHWAY_STANDBY_COUNT``) the supervisor
    also keeps K warm-standby processes tailing the checkpoint root
    (``engine/standby.py``); a worker death is then absorbed by
    promoting one — the survivors rejoin in place and never restart —
    with the whole-group restart above as the fallback tier.

    Elastic rescale: relaunching a supervised run with a DIFFERENT ``-n``
    on the same ``--checkpoint-root`` is supported — the supervisor
    records the new topology in the incarnation lease and the workers
    re-partition checkpointed state by shard range on resume.  With
    ``shrink_on_loss=True`` (or ``PATHWAY_DEGRADED_SHRINK=1``) the
    supervisor performs that rescale on its own when the same worker
    fails every attempt of a spent restart budget — a permanently lost
    host completes the run at the surviving count instead of failing it.
    """
    click.echo(
        f"[pathway_tpu] launching SPMD cluster: {processes} process(es), "
        f"ports {first_port}..{first_port + processes - 1}"
        + (f", supervised (max {max_restarts} restarts)" if supervise else ""),
        err=True,
    )
    run_id = str(uuid.uuid4())
    # every worker must hold the same mesh handshake secret
    # (engine/comm.py); honor a deployment-provided one, else mint one
    # for this run
    env_base = dict(env_base)
    env_base.setdefault("PATHWAY_COMM_SECRET", secrets.token_hex(16))
    # one trace per run: every worker inherits this traceparent, so its
    # epoch/commit/recovery spans correlate into a single trace in any
    # OTLP collector (worker 0 re-broadcasts it over the mesh for workers
    # launched outside spawn); restarts keep it — a recovery is part of
    # the same run's story
    from pathway_tpu.engine.telemetry import mint_traceparent

    env_base.setdefault("TRACEPARENT", mint_traceparent())
    if autoscale:
        # the workers gate their load beacons + autoscaler panel wiring on
        # the same knob the supervisor's controller reads
        env_base["PATHWAY_AUTOSCALE"] = "1"

    if supervise:
        from pathway_tpu.engine.supervisor import (
            ENV_ATTEMPT,
            ENV_INCARNATION,
            Supervisor,
            SupervisorError,
        )

        def spawn_one(
            process_id: int, attempt: int, n_workers: int = processes
        ) -> subprocess.Popen:
            # n_workers is the CURRENT cluster size (the supervisor passes
            # it explicitly so a degraded-mode shrink launches the smaller
            # topology with a matching PATHWAY_PROCESSES)
            env = _cluster_env(
                env_base,
                threads=threads,
                processes=n_workers,
                first_port=first_port,
                process_id=process_id,
                run_id=run_id,
            )
            env[ENV_ATTEMPT] = str(attempt)
            # the supervisor bumps the root's incarnation lease before
            # each attempt and exports it into ITS environ; copy it into
            # the worker env so persistence fencing and the mesh handshake
            # see the incarnation this attempt runs under
            from pathway_tpu.internals.config import env_raw

            incarnation = env_raw(ENV_INCARNATION)
            if incarnation is not None:
                env[ENV_INCARNATION] = incarnation
            # exported by the supervisor around a STANDBY spawn (same
            # env-export trick as the incarnation): the process boots
            # into the tail loop instead of the worker path
            standby_id = env_raw("PATHWAY_STANDBY_ID")
            if standby_id is not None:
                env["PATHWAY_STANDBY_ID"] = standby_id
            return subprocess.Popen([program, *arguments], env=env)

        def echo_post_mortem(post_mortem: dict) -> None:
            for wid, info in sorted(post_mortem.get("workers", {}).items()):
                click.echo(
                    f"[pathway_tpu] worker {wid}: "
                    f"{len(info.get('dumps', []))} flight-recorder dump(s) "
                    f"(last reason: {(info.get('reasons') or [None])[-1]}) — "
                    f"inspect with `pathway_tpu blackbox {checkpoint_root}`",
                    err=True,
                )

        try:
            result = Supervisor(
                spawn_one,
                processes,
                max_restarts=max_restarts,
                checkpoint_root=checkpoint_root,
                shrink_on_loss=shrink_on_loss,
                autoscale=autoscale,
                standbys=standbys,
            ).run()
        except SupervisorError as exc:
            click.echo(f"[pathway_tpu] {exc}", err=True)
            # the crash-loop black boxes are the post-mortem evidence —
            # point the operator at them before giving up
            echo_post_mortem(exc.post_mortem)
            sys.exit(1)
        if result.restarts:
            click.echo(
                f"[pathway_tpu] recovered after {result.restarts} restart(s) "
                f"(last failure: {result.last_failure})",
                err=True,
            )
        for promo in result.promotions:
            click.echo(
                f"[pathway_tpu] standby promotion: standby "
                f"{promo['standby']} adopted worker {promo['worker']} in "
                f"{promo.get('duration_s')}s on attempt "
                f"{promo.get('attempt')} ({promo.get('reason')}); the "
                "surviving workers rejoined in place without a restart",
                err=True,
            )
        for rescale in result.rescales:
            kind = rescale.get("kind")
            if kind == "autoscale":
                click.echo(
                    f"[pathway_tpu] autoscale ({rescale.get('action')}): "
                    f"cluster rescaled {rescale['from']} -> {rescale['to']} "
                    f"worker(s) via live shard handoff on attempt "
                    f"{rescale['attempt']} ({rescale.get('reason')}); "
                    f"{rescale.get('moving_shards')} shard(s) changed owner",
                    err=True,
                )
            elif kind == "autoscale-fallback":
                click.echo(
                    f"[pathway_tpu] autoscale fallback: live handoff "
                    f"{rescale['from']} -> {rescale['to']} worker(s) faulted "
                    f"on attempt {rescale['attempt']}; applied the target "
                    f"topology via restart-based rescale instead "
                    f"({rescale.get('reason')})",
                    err=True,
                )
            else:
                click.echo(
                    f"[pathway_tpu] degraded-mode shrink: worker "
                    f"{rescale['lost_worker']} treated as permanently lost on "
                    f"attempt {rescale['attempt']} — cluster rescaled "
                    f"{rescale['from']} -> {rescale['to']} worker(s); state "
                    "re-partitioned by shard range",
                    err=True,
                )
        # corruption fallback can happen WITHOUT any crash (root damaged at
        # rest before launch): report provenance whenever a worker rejected
        # generations, not only after restarts
        for wid, info in sorted(result.recovery.items()):
            rejected = [g for g, _ in info.get("rejected") or []]
            if not rejected and not result.restarts:
                continue
            click.echo(
                f"[pathway_tpu] worker {wid}: resumed from verified "
                f"generation {info.get('recovered_from')} "
                f"(now at {info.get('generation')})"
                + (f", rejected damaged generation(s) {rejected}"
                   if rejected else ""),
                err=True,
            )
        echo_post_mortem(result.post_mortem)
        sys.exit(0)

    handles: list[subprocess.Popen] = []
    try:
        # spawn inside the try: a mid-spawn failure (EAGAIN, missing
        # program) must still terminate the workers already started, or
        # they hang forever waiting for mesh peers
        for process_id in range(processes):
            handles.append(
                subprocess.Popen(
                    [program, *arguments],
                    env=_cluster_env(
                        env_base,
                        threads=threads,
                        processes=processes,
                        first_port=first_port,
                        process_id=process_id,
                        run_id=run_id,
                    ),
                )
            )
        for handle in handles:
            handle.wait()
    finally:
        for handle in handles:
            handle.terminate()
    codes = [handle.returncode for handle in handles]
    # a signal-killed worker (negative returncode) must not read as success;
    # report it with the conventional 128+signum shell encoding
    sys.exit(max(c if c >= 0 else 128 - c for c in codes))


def _recording_env(
    *,
    access: str | None = None,
    record_path: str | None = None,
    mode: str | None = None,
    continue_after_replay: bool = False,
) -> dict[str, str]:
    """Base environment for record/replay runs (PATHWAY_* protocol)."""
    env = os.environ.copy()
    if record_path is not None:
        env["PATHWAY_REPLAY_STORAGE"] = record_path
    if access is not None:
        env["PATHWAY_SNAPSHOT_ACCESS"] = access
    if mode is not None:
        env["PATHWAY_PERSISTENCE_MODE"] = mode
        env["PATHWAY_REPLAY_MODE"] = mode
    if continue_after_replay:
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    return env


@click.group
@click.version_option(version=pw.__version__, prog_name="pathway_tpu")
def cli() -> None:
    pass


_SPAWN_SETTINGS = {"allow_interspersed_args": False, "show_default": True}


@cli.command(context_settings=_SPAWN_SETTINGS)
@click.option("-t", "--threads", metavar="N", type=click.IntRange(min=1), default=1, help="worker threads per spawned process")
@click.option("-n", "--processes", metavar="N", type=click.IntRange(min=1), default=1, help="cluster size (identical SPMD processes)")
@click.option("--first-port", metavar="PORT", type=int, default=10000, help="base port of the worker TCP mesh")
@click.option("--record", is_flag=True, help="capture every connector's input stream while running")
@click.option("--record-path", type=str, default="record", help="where the captured stream is written")
@click.option(
    "--jax-distributed",
    is_flag=True,
    help="form a multi-host DEVICE mesh too: each process calls "
    "jax.distributed.initialize so jax.devices() spans the cluster "
    "(coordinator derived from the PATHWAY_* env)",
)
@click.option(
    "--supervise",
    is_flag=True,
    help="restart the cluster from the last committed persistence "
    "checkpoint when a worker dies (engine/supervisor.py)",
)
@click.option(
    "--max-restarts",
    metavar="N",
    type=click.IntRange(min=0),
    default=3,
    help="supervised mode: give up after N recoveries",
)
@click.option(
    "--checkpoint-root",
    metavar="PATH",
    type=str,
    default=None,
    help="supervised mode: the program's filesystem persistence root, so "
    "recovery provenance (which verified generation each worker resumed "
    "from) is reported after the run",
)
@click.option(
    "--shrink-on-loss",
    is_flag=True,
    default=None,
    help="supervised mode: when the SAME worker fails every attempt of a "
    "spent restart budget (a permanently lost host, not a crash loop), "
    "rescale the cluster to the surviving count instead of failing — "
    "checkpointed state re-partitions by shard range on resume "
    "(PATHWAY_DEGRADED_SHRINK=1 is the env form)",
)
@click.option(
    "--autoscale",
    is_flag=True,
    default=None,
    help="supervised mode: arm the load-adaptive scale controller — "
    "sustained output staleness grows the cluster, sustained idleness "
    "shrinks it, applied by live shard handoff with restart fallback "
    "(bounds/thresholds via PATHWAY_AUTOSCALE_* knobs; "
    "PATHWAY_AUTOSCALE=1 is the env form; requires --checkpoint-root)",
)
@click.option(
    "--standbys",
    metavar="K",
    type=click.IntRange(min=0),
    default=None,
    help="supervised mode: keep K warm-standby processes tailing the "
    "checkpoint root (engine/standby.py) so a worker death is absorbed "
    "by promoting one — survivors rejoin in place, no group restart — "
    "with restart as the fallback tier (PATHWAY_STANDBY_COUNT is the "
    "env form; requires --checkpoint-root)",
)
@click.argument("program")
@click.argument("arguments", nargs=-1)
def spawn(threads, processes, first_port, record, record_path, jax_distributed, supervise, max_restarts, checkpoint_root, shrink_on_loss, autoscale, standbys, program, arguments):
    """Run PROGRAM as an SPMD cluster of identical processes.

    Re-running a supervised program with a different ``-n`` against the
    same ``--checkpoint-root`` performs an elastic rescale: resume
    re-partitions the committed snapshots by shard range under the new
    worker count (see docs/fault_tolerance.md, "Elastic rescale").
    """
    env = (
        _recording_env(
            access="record", record_path=record_path, continue_after_replay=True
        )
        if record
        else os.environ.copy()
    )
    if jax_distributed:
        env["PATHWAY_JAX_DISTRIBUTED"] = "1"
    spawn_program(
        threads=threads,
        processes=processes,
        first_port=first_port,
        program=program,
        arguments=arguments,
        env_base=env,
        supervise=supervise,
        max_restarts=max_restarts,
        checkpoint_root=checkpoint_root,
        shrink_on_loss=shrink_on_loss,
        autoscale=autoscale,
        standbys=standbys,
    )


@cli.command(context_settings=_SPAWN_SETTINGS)
@click.option("-t", "--threads", metavar="N", type=click.IntRange(min=1), default=1, help="worker threads per spawned process")
@click.option("-n", "--processes", metavar="N", type=click.IntRange(min=1), default=1, help="cluster size (identical SPMD processes)")
@click.option("--first-port", metavar="PORT", type=int, default=10000, help="base port of the worker TCP mesh")
@click.option("--record-path", type=str, default="record", help="where the captured stream was written")
@click.option(
    "--mode",
    type=click.Choice(["batch", "speedrun"], case_sensitive=False),
    help="replay pacing: one batch, or recorded timing",
)
@click.option(
    "--continue",
    "continue_after_replay",
    is_flag=True,
    help="after the recording drains, keep consuming live connector data",
)
@click.argument("program")
@click.argument("arguments", nargs=-1)
def replay(threads, processes, first_port, record_path, mode, continue_after_replay, program, arguments):
    """Re-run PROGRAM against a previously captured input stream."""
    spawn_program(
        threads=threads,
        processes=processes,
        first_port=first_port,
        program=program,
        arguments=arguments,
        env_base=_recording_env(
            access="replay",
            record_path=record_path,
            mode=mode,
            continue_after_replay=continue_after_replay,
        ),
    )


@cli.command()
@click.option(
    "--worker",
    metavar="N",
    type=int,
    default=None,
    help="audit only this worker's checkpoint shard",
)
@click.option(
    "--json", "as_json", is_flag=True, help="emit the machine-readable report"
)
@click.option(
    "--repair",
    is_flag=True,
    help="quarantine damaged generations above each worker's newest "
    "verified one (moved to quarantine/<worker>/, kept for forensics), "
    "then re-audit — the deliberate unblock for configurations that "
    "refuse to fall back silently",
)
@click.argument("root", type=click.Path(exists=True, file_okay=False))
def scrub(worker, as_json, repair, root):
    """Audit a filesystem persistence ROOT offline.

    Verifies every retained checkpoint generation chunk-by-chunk
    (integrity frames + manifest digests) without mutating anything
    (unless --repair), and reports per-generation health.  Exits non-zero
    when any worker's NEWEST generation fails verification — recovery
    would silently fall back to an older generation, which deserves
    operator attention.
    """
    import json as _json

    from pathway_tpu.engine.persistence import (
        FileBackend,
        repair_root,
        scrub_root,
    )

    backend = FileBackend(root)
    if repair:
        for action in repair_root(backend, worker=worker):
            click.echo(f"[repair] {action}", err=True)
    report = scrub_root(backend, worker=worker)
    if as_json:
        click.echo(_json.dumps(report, indent=2, sort_keys=True))
    else:
        click.echo(f"scrub of {report['backend']}")
        if report.get("error"):
            click.echo(f"  ERROR: {report['error']}")
        lease = report.get("lease")
        if lease is not None:
            if lease.get("ok"):
                beacons = lease.get("progress_workers") or []
                click.echo(
                    f"  lease: incarnation {lease['incarnation']} "
                    f"(owner: {lease.get('owner')})"
                    + (f", topology {lease['workers']} worker(s)"
                       if isinstance(lease.get("workers"), int) else "")
                    + (f", progress beacons for workers {beacons}"
                       if beacons else "")
                )
            else:
                click.echo(f"  lease: DAMAGED — {lease.get('error')}")
            for sid, beacon in sorted((lease.get("standbys") or {}).items()):
                cursors = beacon.get("cursors") or {}
                trail = ", ".join(
                    f"w{w}@g{g}"
                    for w, g in sorted(
                        cursors.items(), key=lambda item: int(item[0])
                    )
                )
                click.echo(
                    f"  standby {sid}: apply lag {beacon.get('lag_s')}s, "
                    f"{beacon.get('verified_chunks')} chunk(s) verified"
                    + (f", cursors {trail}" if trail
                       else ", no generations applied yet")
                )
            promos = lease.get("promotions") or []
            if promos:
                click.echo(f"  promotion history ({len(promos)}):")
                for p in promos:
                    click.echo(
                        f"    standby {p.get('standby')} -> worker "
                        f"{p.get('worker')} in {p.get('duration_s')}s on "
                        f"attempt {p.get('attempt')} ({p.get('reason')})"
                    )
            promote = lease.get("promote")
            if promote and promote.get("pending_request"):
                click.echo(
                    "  promotion IN FLIGHT (acks: "
                    f"{', '.join(promote.get('acks') or []) or 'none'})"
                )
        topo = report.get("topology")
        if topo is not None:
            history = topo.get("history") or []
            if len(history) > 1:
                trail = " -> ".join(
                    f"{h.get('workers')}@inc{h.get('incarnation')}"
                    for h in history
                )
                click.echo(f"  rescale history: {trail}")
        bb = report.get("blackbox")
        if bb is not None:
            click.echo(
                f"  blackbox: {bb['dumps']} flight-recorder dump(s) "
                f"for worker(s) {bb['workers']}"
                + (f", {len(bb['unreadable'])} unreadable"
                   if bb["unreadable"] else "")
            )
        if not report["workers"] and not report.get("error"):
            click.echo("  no checkpoint state found")
        for wid, wrep in sorted(report["workers"].items()):
            status = "OK" if wrep["ok"] else "DAMAGED"
            if wrep.get("orphaned"):
                status = f"ORPHANED ({wrep.get('status', 'fenced, pending GC')})"
            elif wrep.get("pending_repartition"):
                status += " (old topology, pending repartition)"
            click.echo(
                f"  worker {wid}: {status} — newest generation "
                f"{wrep['newest']}, newest verified {wrep['newest_verified']}"
                + (" (legacy pre-manifest metadata)"
                   if wrep["legacy_metadata"] else "")
            )
            pointer_error = (wrep.get("pointer") or {}).get("error")
            if pointer_error:
                click.echo(f"    metadata pointer: {pointer_error}")
            for entry in wrep["generations"]:
                mark = "ok" if entry["ok"] else "CORRUPT"
                stamp = entry.get("incarnation")
                topo_stamp = entry.get("topology")
                notes = []
                if stamp:
                    notes.append(f"incarnation {stamp}")
                if topo_stamp:
                    notes.append(f"topology {topo_stamp}")
                if entry.get("repartitioned_from"):
                    notes.append(
                        f"repartitioned from {entry['repartitioned_from']}"
                    )
                click.echo(
                    f"    generation {entry['generation']}: {mark}"
                    + (f" ({', '.join(notes)})" if notes else "")
                )
                for problem in entry["problems"]:
                    click.echo(f"      - {problem}")
    click.echo(
        f"[pathway_tpu] scrub: {'clean' if report['ok'] else 'DAMAGE FOUND'}",
        err=True,
    )
    sys.exit(0 if report["ok"] else 1)


@cli.command()
@click.option(
    "--worker",
    metavar="N",
    type=int,
    default=None,
    help="show only this worker's dumps",
)
@click.option(
    "--tail",
    metavar="N",
    type=click.IntRange(min=1),
    default=20,
    help="events to show from the end of each dump's ring",
)
@click.option(
    "--json", "as_json", is_flag=True, help="emit the raw dumps as JSON"
)
@click.argument("root", type=click.Path(exists=True, file_okay=False))
def blackbox(worker, tail, as_json, root):
    """Pretty-print crash flight-recorder dumps under a persistence ROOT.

    Workers dump their bounded event ring (epoch transitions, commit
    publishes, comm reconnects, injected faults) to ``<ROOT>/blackbox/``
    when they crash or a fault fires; the supervisor summarizes them on
    ``SupervisorResult.post_mortem``.  This command renders the full
    dumps for post-mortem analysis.  Exits non-zero when no dump exists.
    """
    import datetime
    import json as _json

    from pathway_tpu.engine.flight_recorder import gather_dumps

    dumps = gather_dumps(root)
    if worker is not None:
        dumps = {w: d for w, d in dumps.items() if w == worker}
    if not dumps:
        # missing or empty blackbox/: a clear non-zero exit, whatever the
        # output mode — an operator piping --json must still see why
        click.echo(
            f"[pathway_tpu] no flight-recorder dumps under {root}/blackbox "
            "— nothing crashed there, or this is not a persistence root",
            err=True,
        )
        if as_json:
            click.echo(_json.dumps({}))
        sys.exit(1)
    if as_json:
        click.echo(_json.dumps(dumps, indent=2, sort_keys=True))
        sys.exit(0)

    def when(ts):
        # best-effort like the gather layer: a parseable-but-partial dump
        # (hand-edited, older format) must render, not traceback
        if not isinstance(ts, (int, float)):
            return "--:--:--.---"
        return datetime.datetime.fromtimestamp(ts).strftime("%H:%M:%S.%f")[:-3]

    for wid, payloads in sorted(dumps.items()):
        for payload in payloads:
            events = payload.get("events") or []
            click.echo(
                f"worker {wid} · attempt {payload.get('attempt')} · "
                f"pid {payload.get('pid')} · run {payload.get('run_id')}"
            )
            click.echo(f"  reason: {payload.get('reason')}")
            if payload.get("trace_parent"):
                click.echo(f"  trace:  {payload['trace_parent']}")
            click.echo(
                f"  events: {len(events)} recorded, last {min(tail, len(events))}:"
            )
            for ev in events[-tail:]:
                detail = ", ".join(
                    f"{k}={v}"
                    for k, v in ev.items()
                    if k not in ("ts", "mono", "seq", "kind")
                )
                click.echo(
                    f"    {when(ev.get('ts'))}  #{str(ev.get('seq', '?')):>5}  "
                    f"{str(ev.get('kind', '?')):<22}{detail}"
                )
            profile = payload.get("profiler")
            if profile:
                # where the time went, not just what happened: the final
                # profiler snapshot captured at dump time
                from pathway_tpu.engine.profiler import render_snapshot

                for line in render_snapshot(profile).splitlines():
                    click.echo(f"  {line}")
            freshness = payload.get("freshness")
            if freshness:
                # ...and what was STUCK: the final watermark/backlog
                # snapshot (engine/freshness.py)
                from pathway_tpu.engine.freshness import render_freshness

                for line in render_freshness(freshness).splitlines():
                    click.echo(f"  {line}")
            device = payload.get("device")
            if device:
                # ...and what the DEVICE was doing: the final executor
                # snapshot (pathway_tpu/device/telemetry.py)
                from pathway_tpu.device import render_device_snapshot

                for line in render_device_snapshot(device).splitlines():
                    click.echo(f"  {line}")
            else:
                # pre-device-observability dumps carry no device key —
                # an explicit empty state, never a KeyError
                click.echo("  device: (no snapshot in this dump)")
            autoscaler = payload.get("autoscaler")
            if autoscaler:
                # ...and what the scale controller was deciding: the
                # supervisor-maintained state (engine/autoscaler.py) at
                # dump time, with the tail of the decision log
                click.echo(
                    "  autoscaler: target "
                    f"{autoscaler.get('target_workers')} worker(s) · "
                    f"budget left {autoscaler.get('budget_left')} · "
                    f"handoff state "
                    f"{autoscaler.get('handoff_state') or 'idle'}"
                )
                for entry in (autoscaler.get("decisions") or [])[-5:]:
                    click.echo(
                        f"    {entry.get('action', '?'):<18}"
                        + ", ".join(
                            f"{k}={v}"
                            for k, v in entry.items()
                            if k not in ("action", "at")
                        )
                    )
            serving = payload.get("serving")
            if serving:
                # ...and what the SERVING edge was refusing: admission
                # occupancy + shed/drain state (engine/serving.py) at
                # dump time, with the quarantine tail
                limits = serving.get("limits") or {}
                flags = [
                    flag
                    for flag, on in (
                        ("degraded", serving.get("degraded")),
                        ("draining", serving.get("draining")),
                        ("admission off", not serving.get("enabled", True)),
                    )
                    if on
                ]
                click.echo(
                    f"  serving: {serving.get('inflight')}"
                    f"/{limits.get('inflight')} in flight · queue "
                    f"{serving.get('queue_depth')}/{limits.get('queue')}"
                    + (" · " + ", ".join(flags) if flags else "")
                )
                if serving.get("quarantined_total"):
                    click.echo(
                        "    quarantined "
                        f"{serving['quarantined_total']} request(s), last:"
                    )
                    for entry in serving.get("quarantine") or []:
                        click.echo(
                            f"      key={entry.get('key')} "
                            f"{entry.get('error')}"
                        )
    sys.exit(0)


@cli.command()
@click.option(
    "--json", "as_json", is_flag=True, help="emit the report as JSON"
)
@click.option(
    "--rules",
    "rule_ids",
    metavar="ID[,ID...]",
    default=None,
    help="run only these rule ids (default: every rule)",
)
@click.option(
    "--list-rules", is_flag=True, help="print the rule catalogue and exit"
)
@click.option(
    "--update-config-docs",
    is_flag=True,
    help="regenerate docs/configuration.md from the env-knob registry "
    "(internals/config.py:ENV_KNOBS) and exit",
)
@click.argument("paths", nargs=-1, type=click.Path(exists=True))
def lint(as_json, rule_ids, list_rules, update_config_docs, paths):
    """Run the repo-native static analyzer over PATHS.

    Default paths are the installed ``pathway_tpu`` package and its
    sibling ``tests/`` tree.  Rules prove thread-context safety (no
    blocking calls on the epoch loop or signal paths, timed waits on
    supervised background threads), lock-order consistency, env-knob and
    metric-name registry discipline, jit recompile discipline, and the
    chaos-suite sleep policy — see ``docs/static_analysis.md``.

    Exits non-zero when any unsuppressed finding remains.  Suppressions
    (``# pathway-lint: disable=<rule> — <reason>``) are audited: a
    reasonless or useless suppression is itself a finding.
    """
    from pathway_tpu.analysis import RULES, report_to_text, run_lint

    if list_rules:
        width = max(len(rid) for rid in RULES)
        for rid in sorted(RULES):
            click.echo(f"{rid:<{width}}  {RULES[rid].doc}")
        sys.exit(0)
    pkg_dir = os.path.dirname(os.path.abspath(pw.__file__))
    repo_root = os.path.dirname(pkg_dir)
    if update_config_docs:
        from pathway_tpu.internals.config import render_env_docs

        doc_path = os.path.join(repo_root, "docs", "configuration.md")
        os.makedirs(os.path.dirname(doc_path), exist_ok=True)
        with open(doc_path, "w", encoding="utf-8") as f:
            f.write(render_env_docs())
        click.echo(f"[pathway_tpu] wrote {doc_path}")
        sys.exit(0)
    if not paths:
        paths = [pkg_dir]
        tests_dir = os.path.join(repo_root, "tests")
        if os.path.isdir(tests_dir):
            paths.append(tests_dir)
    selected = None
    if rule_ids:
        selected = [r.strip() for r in rule_ids.split(",") if r.strip()]
    try:
        report = run_lint(paths, rules=selected)
    except ValueError as exc:  # unknown rule id
        click.echo(f"[pathway_tpu] {exc}", err=True)
        sys.exit(2)
    click.echo(report_to_text(report, as_json=as_json))
    sys.exit(0 if report.ok else 1)


@cli.command()
@click.option(
    "--top",
    metavar="N",
    type=click.IntRange(min=1),
    default=None,
    help="operators to show (default: the PATHWAY_PROFILE_TOP knob)",
)
@click.option(
    "--json", "as_json", is_flag=True, help="emit the raw snapshot(s) as JSON"
)
@click.argument("source", type=click.Path(exists=True))
def profile(top, as_json, source):
    """Render a per-operator attribution tree from profiler output.

    SOURCE is either a profiler snapshot JSON (written at run end when
    ``PATHWAY_PROFILE=1`` and ``PATHWAY_PROFILE_OUTPUT=<path>`` are set)
    or a filesystem persistence root, whose flight-recorder dumps under
    ``blackbox/`` carry final profiler snapshots (see
    ``docs/observability.md``).  Exits non-zero when SOURCE holds no
    profile.
    """
    import json as _json

    from pathway_tpu.engine.profiler import render_snapshot
    from pathway_tpu.internals.config import env_int

    top = top or env_int("PATHWAY_PROFILE_TOP")
    # (label, profiler snapshot, device snapshot or None) — positionally
    # paired, because one worker/attempt can leave several dumps
    # (watchdog + crash) whose labels collide; ABSENT marks a bare
    # PATHWAY_PROFILE_OUTPUT snapshot with no dump context at all, and
    # None a dump that predates device observability (explicit empty
    # state)
    ABSENT = object()
    snapshots: list[tuple[str, dict, Any]] = []
    if os.path.isdir(source):
        from pathway_tpu.engine.flight_recorder import gather_dumps

        for wid, payloads in sorted(gather_dumps(source).items()):
            for payload in payloads:
                label = f"worker {wid} · attempt {payload.get('attempt')}"
                prof = payload.get("profiler")
                if prof:
                    snapshots.append((label, prof, payload.get("device")))
    else:
        try:
            with open(source, encoding="utf-8") as f:
                payload = _json.load(f)
        except (OSError, ValueError) as exc:
            click.echo(f"[pathway_tpu] unreadable snapshot: {exc}", err=True)
            sys.exit(2)
        # tolerate any JSON top level (the command's own --json output is
        # a list) — anything without a snapshot dict falls through to the
        # friendly no-profile exit below
        prof = (
            payload.get("profiler", payload)
            if isinstance(payload, dict)
            else None
        )
        if isinstance(prof, dict) and "operators" in prof:
            # a flight-recorder dump file gets the same device section
            # (or empty state) as the directory form; a bare
            # PATHWAY_PROFILE_OUTPUT snapshot has no dump context and
            # gets neither
            device = (
                payload.get("device")
                if isinstance(payload, dict) and "profiler" in payload
                else ABSENT
            )
            snapshots.append((source, prof, device))
    if not snapshots:
        click.echo(
            f"[pathway_tpu] no profiler snapshot in {source} — run with "
            "PATHWAY_PROFILE=1 (and PATHWAY_PROFILE_OUTPUT=<path>, or read "
            "a persistence root with flight-recorder dumps)",
            err=True,
        )
        sys.exit(1)
    if as_json:
        # a list, not a dict: one worker/attempt can leave several dumps
        # (watchdog + crash) whose labels collide — none may be dropped
        entries = []
        for label, snap, device in snapshots:
            entry: dict = {"label": label, "snapshot": snap}
            if device is not ABSENT:
                # the machine-readable form carries the same device
                # section the text render shows (null = a dump that
                # predates device observability)
                entry["device"] = device
            entries.append(entry)
        click.echo(_json.dumps(entries, indent=2, sort_keys=True))
        sys.exit(0)
    for label, snap, device in snapshots:
        if len(snapshots) > 1:
            click.echo(label)
        click.echo(render_snapshot(snap, top=top))
        if device is ABSENT:
            continue
        if device:
            from pathway_tpu.device import render_device_snapshot

            click.echo(render_device_snapshot(device))
        else:
            click.echo("device: (no snapshot in this dump)")
    sys.exit(0)


@cli.command()
@click.option(
    "--url",
    metavar="URL",
    type=str,
    default=None,
    help="full /status URL (overrides --port/--process-id)",
)
@click.option(
    "--port",
    metavar="PORT",
    type=int,
    default=None,
    help="monitoring HTTP port (default: PATHWAY_MONITORING_HTTP_PORT, "
    "else 20000 + process id)",
)
@click.option(
    "--process-id",
    metavar="N",
    type=int,
    default=0,
    help="worker whose endpoint to poll (port defaults to 20000 + N)",
)
@click.option(
    "--interval",
    metavar="SECONDS",
    type=float,
    default=None,
    help="refresh interval (default: the PATHWAY_STATUS_REFRESH_S knob)",
)
@click.option(
    "--once", is_flag=True, help="render a single frame and exit (no loop)"
)
@click.option(
    "--json", "as_json", is_flag=True, help="emit the raw /status JSON"
)
def top(url, port, process_id, interval, once, as_json):
    """Live per-operator backlog + freshness view of a running pipeline.

    Polls ``GET /status`` on the monitoring HTTP server (enable it with
    ``pw.run(with_http_server=True)`` or ``PATHWAY_MONITORING_HTTP_PORT``)
    and renders epoch rate, per-output staleness and end-to-end latency
    quantiles (``freshness.*``), the ranked ``backlog.*`` wait points,
    and the per-operator progress table — see ``docs/observability.md``,
    "Freshness & backpressure".  Exits non-zero with a clear message when
    the endpoint is unreachable.
    """
    import json as _json
    import time as _time_mod

    from pathway_tpu.internals.config import env_float
    from pathway_tpu.internals.top import (
        StatusUnavailable,
        fetch_status,
        render_top,
    )

    url = _monitoring_url(url, port, process_id, "status")
    if interval is None:
        interval = env_float("PATHWAY_STATUS_REFRESH_S")  # declared default 1.0
    # an explicit small value clamps (never silently reverts to the
    # default); 0.1 s is the floor so a typo cannot hot-spin the server
    interval = max(0.1, float(interval))
    prev = None
    prev_t = None
    while True:
        try:
            status = fetch_status(url)
        except StatusUnavailable as exc:
            click.echo(f"[pathway_tpu] {exc}", err=True)
            sys.exit(1)
        now = _time_mod.monotonic()
        if as_json:
            click.echo(_json.dumps(status, indent=2, sort_keys=True))
        else:
            if not once:
                click.clear()
            # epoch rate derives from the MEASURED elapsed time between
            # polls, not the configured interval — slow fetches must not
            # overstate the rate
            click.echo(
                render_top(
                    status,
                    prev,
                    interval_s=(now - prev_t) if prev_t else None,
                )
            )
        if once:
            sys.exit(0)
        prev, prev_t = status, now
        _time_mod.sleep(interval)


@cli.command()
@click.argument(
    "dump", required=False, type=click.Path(exists=True, dir_okay=False)
)
@click.option(
    "--url",
    metavar="URL",
    type=str,
    default=None,
    help="full /status URL (overrides --port/--process-id)",
)
@click.option(
    "--port",
    metavar="PORT",
    type=int,
    default=None,
    help="monitoring HTTP port (default: PATHWAY_MONITORING_HTTP_PORT, "
    "else 20000 + process id)",
)
@click.option(
    "--process-id",
    metavar="N",
    type=int,
    default=0,
    help="worker whose endpoint to poll (port defaults to 20000 + N)",
)
@click.option(
    "-n",
    "--limit",
    metavar="N",
    type=int,
    default=10,
    help="waterfalls to render (default 10)",
)
@click.option(
    "--recent",
    is_flag=True,
    help="newest-first instead of slowest-first",
)
@click.option(
    "--json", "as_json", is_flag=True, help="emit the raw trace JSON"
)
def requests(dump, url, port, process_id, limit, recent, as_json):
    """Slowest-request waterfalls from the live span buffer or a dump.

    Reads the finished-request trace ring (``engine/tracing.py``) either
    from a running pipeline's ``GET /status`` ``requests`` section or —
    with a DUMP argument — from a flight-recorder dump file's
    ``requests`` payload, and renders each trace as a span waterfall:
    admission, coalesce, device dispatch, and generation stages with
    their offsets and durations.  See ``docs/observability.md``,
    "Request tracing & SLOs".
    """
    import json as _json

    from pathway_tpu.internals.top import (
        StatusUnavailable,
        fetch_status,
        render_requests,
    )

    if dump is not None:
        try:
            with open(dump) as f:
                payload = _json.load(f)
        except (OSError, ValueError) as exc:
            click.echo(f"[pathway_tpu] cannot read dump {dump}: {exc}", err=True)
            sys.exit(1)
        section = payload.get("requests") or {}
    else:
        status_url = _monitoring_url(url, port, process_id, "status")
        try:
            status = fetch_status(status_url)
        except StatusUnavailable as exc:
            click.echo(f"[pathway_tpu] {exc}", err=True)
            sys.exit(1)
        section = status.get("requests") or {}
    traces = section.get("recent" if recent else "slowest") or []
    if as_json:
        click.echo(_json.dumps(traces[:limit], indent=2, sort_keys=True))
        sys.exit(0)
    click.echo(render_requests(traces, limit=limit))
    sys.exit(0)


def _monitoring_url(url: str | None, port: int | None, process_id: int,
                    endpoint: str) -> str:
    """Resolve a monitoring-server URL the way ``top`` does: explicit
    ``--url`` wins, else ``--port``/``PATHWAY_MONITORING_HTTP_PORT``/the
    20000 + process-id default, with ``endpoint`` as the path."""
    if url is not None:
        return url
    from pathway_tpu.engine.http_server import monitoring_port
    from pathway_tpu.internals.config import env_int

    if port is None:
        port = env_int("PATHWAY_MONITORING_HTTP_PORT")
    return f"http://127.0.0.1:{monitoring_port(process_id, port)}/{endpoint}"


@cli.command()
@click.option(
    "--url",
    metavar="URL",
    type=str,
    default=None,
    help="full /trace URL (overrides --port/--process-id)",
)
@click.option(
    "--port",
    metavar="PORT",
    type=int,
    default=None,
    help="monitoring HTTP port (default: PATHWAY_MONITORING_HTTP_PORT, "
    "else 20000 + process id)",
)
@click.option(
    "--process-id",
    metavar="N",
    type=int,
    default=0,
    help="worker whose device to trace (port defaults to 20000 + N)",
)
@click.option(
    "--seconds",
    metavar="S",
    type=float,
    default=3.0,
    show_default=True,
    help="capture duration",
)
def trace(url, port, process_id, seconds):
    """Capture an on-demand jax.profiler trace from a running worker.

    Asks the worker's monitoring HTTP server (``GET /trace?seconds=N``)
    to run ``jax.profiler`` start/stop IN the worker process and dump a
    TensorBoard-viewable trace directory under the worker's
    ``PATHWAY_DEVICE_TRACE_DIR`` — see docs/observability.md, "Device
    observability".  Exits non-zero with the server's reason when
    capture is unavailable (no trace dir configured, capture already
    running, endpoint unreachable).
    """
    import json as _json
    import urllib.error
    import urllib.request

    target = _monitoring_url(url, port, process_id, "trace")
    sep = "&" if "?" in target else "?"
    target = f"{target}{sep}seconds={float(seconds)}"
    click.echo(
        f"[pathway_tpu] capturing {seconds:g} s of device trace via "
        f"{target} ...",
        err=True,
    )
    try:
        # the server blocks for the capture duration; pad the timeout
        with urllib.request.urlopen(target, timeout=seconds + 30.0) as r:
            payload = _json.loads(r.read().decode())
    except urllib.error.HTTPError as exc:
        try:
            reason = _json.loads(exc.read().decode()).get("error", str(exc))
        except Exception:  # noqa: BLE001 - error body is best-effort
            reason = str(exc)
        click.echo(f"[pathway_tpu] trace capture failed: {reason}", err=True)
        sys.exit(1)
    except (OSError, ValueError) as exc:
        click.echo(
            f"[pathway_tpu] cannot reach {target} ({exc}) — is the pipeline "
            "running with with_http_server=True (or "
            "PATHWAY_MONITORING_HTTP_PORT set)?",
            err=True,
        )
        sys.exit(1)
    trace_dir = payload.get("trace_dir")
    click.echo(f"[pathway_tpu] trace written to {trace_dir}")
    click.echo(f"[pathway_tpu] view with: tensorboard --logdir {trace_dir}", err=True)
    sys.exit(0)


@cli.command()
@click.option(
    "--url",
    metavar="URL",
    type=str,
    default=None,
    help="full /status URL (overrides --port/--process-id)",
)
@click.option(
    "--port",
    metavar="PORT",
    type=int,
    default=None,
    help="monitoring HTTP port (default: PATHWAY_MONITORING_HTTP_PORT, "
    "else 20000 + process id)",
)
@click.option(
    "--process-id",
    metavar="N",
    type=int,
    default=0,
    help="worker whose batch distribution to read",
)
@click.option(
    "--max-buckets",
    metavar="K",
    type=click.IntRange(min=1),
    default=8,
    show_default=True,
    help="bucket-set size budget (each bucket is one compile per callable)",
)
@click.option(
    "--json", "as_json", is_flag=True, help="emit the report as JSON"
)
@click.argument(
    "root", type=click.Path(exists=True, file_okay=False), required=False
)
def buckets(url, port, process_id, max_buckets, as_json, root):
    """Replay the observed batch-size distribution; suggest better buckets.

    Reads the ragged batch sizes the DeviceExecutor actually saw — live
    from a running worker's ``GET /status`` device section, or post-hoc
    from the flight-recorder dumps under a persistence ROOT — replays
    them against the default power-of-two policy, and reports the bucket
    set of at most ``--max-buckets`` sizes that minimizes padding waste
    (``device/bucketing.py:suggest_buckets``).  Exits non-zero when no
    batch distribution is available.
    """
    import json as _json

    from pathway_tpu.device.bucketing import (
        BucketPolicy,
        next_pow2,
        replay_waste,
        suggest_buckets,
    )

    size_counts: dict[int, int] = {}
    truncated = False
    observed_max_batch: int | None = None
    if root is not None:
        from pathway_tpu.engine.flight_recorder import gather_dumps

        for _wid, payloads in sorted(gather_dumps(root).items()):
            # the accountant ledger is cumulative PER PROCESS: a worker
            # attempt that dumped twice (watchdog then crash) repeats its
            # earlier batches in the later dump — count only the newest
            # dump of each attempt, summing across attempts (each attempt
            # is a fresh process)
            newest_per_attempt: dict[Any, dict] = {}
            for payload in payloads:
                key = payload.get("attempt")
                prev = newest_per_attempt.get(key)
                if prev is None or (payload.get("dumped_at") or 0) >= (
                    prev.get("dumped_at") or 0
                ):
                    newest_per_attempt[key] = payload
            for payload in newest_per_attempt.values():
                device_snap = payload.get("device") or {}
                try:
                    observed_max_batch = int(device_snap["default_max_batch"])
                except (KeyError, TypeError, ValueError):
                    pass
                sizes = (device_snap.get("cost") or {}).get(
                    "batch_sizes"
                ) or {}
                for size, count in sizes.items():
                    try:
                        size_counts[int(size)] = (
                            size_counts.get(int(size), 0) + int(count)
                        )
                    except (TypeError, ValueError):
                        continue
        source = f"flight-recorder dumps under {root}"
    else:
        from pathway_tpu.engine.metrics import split_labeled_name
        from pathway_tpu.internals.top import StatusUnavailable, fetch_status

        target = _monitoring_url(url, port, process_id, "status")
        try:
            status = fetch_status(target)
        except StatusUnavailable as exc:
            click.echo(f"[pathway_tpu] {exc}", err=True)
            sys.exit(1)
        device_section = status.get("device") or {}
        if device_section.get("device.batch.max"):
            observed_max_batch = int(device_section["device.batch.max"])
        for key, value in device_section.items():
            base, labels = split_labeled_name(key)
            if base != "device.batch.rows" or "rows" not in labels:
                continue
            try:
                size_counts[int(labels["rows"])] = int(value)
            except (TypeError, ValueError):
                continue
        source = target
        # the live feed exports only the most-frequent sizes
        # (device/telemetry.py:BATCH_SIZE_EXPORT_TOP); at the cap the
        # tail was dropped and the report must say so
        from pathway_tpu.device.telemetry import BATCH_SIZE_EXPORT_TOP

        truncated = len(size_counts) >= BATCH_SIZE_EXPORT_TOP
    if not size_counts:
        click.echo(
            f"[pathway_tpu] no batch-size distribution in {source} — the "
            "DeviceExecutor has not dispatched yet (or the dump predates "
            "device observability)",
            err=True,
        )
        sys.exit(1)
    largest = max(size_counts)
    # the baseline is the ANALYZED RUN's default policy: batches above
    # its max split into full-bucket chunks, so replaying against
    # next_pow2(largest) would invent waste the run never paid.  The
    # snapshot/status carries the run's PATHWAY_DEVICE_MAX_BATCH; the
    # analyst's own env is only the last-resort fallback (pre-PR-12
    # dumps)
    if observed_max_batch is None:
        from pathway_tpu.internals.config import env_int

        observed_max_batch = env_int("PATHWAY_DEVICE_MAX_BATCH")
    current = BucketPolicy(
        max_bucket=min(next_pow2(largest), int(observed_max_batch))
    ).buckets()
    current_pad, real = replay_waste(size_counts, current)
    suggested = suggest_buckets(size_counts, max_buckets=max_buckets)
    suggested_pad, _ = replay_waste(size_counts, suggested)

    def frac(pad: int) -> float:
        return pad / (pad + real) if (pad + real) else 0.0

    report = {
        "source": source,
        "batches": sum(size_counts.values()),
        "distinct_sizes": len(size_counts),
        "truncated": truncated,
        "largest": largest,
        "real_rows": real,
        "current": {
            "buckets": list(current),
            "pad_rows": current_pad,
            "waste_fraction": frac(current_pad),
        },
        "suggested": {
            "buckets": list(suggested),
            "pad_rows": suggested_pad,
            "waste_fraction": frac(suggested_pad),
        },
    }
    if as_json:
        click.echo(_json.dumps(report, indent=2, sort_keys=True))
        sys.exit(0)
    click.echo(
        f"batch distribution: {report['batches']} batch(es), "
        f"{report['distinct_sizes']} distinct size(s), largest {largest} "
        f"({source})"
    )
    if truncated:
        click.echo(
            "  note: the live /status feed exports only the most-frequent "
            "sizes — the tail of the distribution was dropped; read a "
            "flight-recorder root for the full ledger"
        )
    click.echo(
        f"  power-of-two policy {current}: {current_pad} pad row(s) "
        f"({frac(current_pad):.1%} waste)"
    )
    click.echo(
        f"  suggested buckets   {suggested}: {suggested_pad} pad row(s) "
        f"({frac(suggested_pad):.1%} waste) — "
        f"{len(suggested)} compile(s) per callable"
    )
    if suggested_pad < current_pad:
        click.echo(
            f"  apply with DeviceExecutor.register(..., policy=BucketPolicy("
            f"sizes={suggested})) for the hot callables"
        )
    else:
        click.echo("  the power-of-two policy is already near-optimal here")
    sys.exit(0)


def _load_harness():
    """Import ``benchmarks/harness.py`` by path (the benchmarks tree sits
    beside the package, not inside it)."""
    import importlib.util

    pkg_dir = os.path.dirname(os.path.abspath(pw.__file__))
    path = os.path.join(os.path.dirname(pkg_dir), "benchmarks", "harness.py")
    if not os.path.isfile(path):
        raise click.ClickException(
            f"benchmark harness not found at {path} (the `bench` command "
            "needs the repository's benchmarks/ tree)"
        )
    name = "pathway_bench_harness"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    # registered before exec: dataclass decorators resolve their module
    # through sys.modules
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


@cli.command()
@click.option(
    "--smoke/--full",
    "smoke",
    default=True,
    help="suite scale: smoke (small sizes, tier-1-friendly) or full",
)
@click.option(
    "--check",
    is_flag=True,
    help="compare against committed baselines (benchmarks/baselines/); "
    "exit non-zero on a regression past the noise-tolerant thresholds",
)
@click.option(
    "--update-baselines",
    is_flag=True,
    help="write this run's medians/IQR as the new baselines",
)
@click.option(
    "--update-results",
    is_flag=True,
    help="regenerate the harness tables in benchmarks/RESULTS.md",
)
@click.option("--reps", metavar="N", type=click.IntRange(min=1), default=None,
              help="repetitions per benchmark (default: per-mode)")
@click.option("--only", metavar="NAME", multiple=True,
              help="run only these benchmarks (repeatable)")
@click.option("--baseline-dir", metavar="PATH", type=str, default=None,
              help="baseline directory override")
@click.option("--json", "json_path", metavar="PATH", type=str, default=None,
              help="also write the machine-readable results JSON here")
def bench(smoke, check, update_baselines, update_results, reps, only,
          baseline_dir, json_path):
    """Run the benchmark suite and check for regressions.

    Runs the repository's host benchmarks (``benchmarks/host_*.py`` and
    friends) in smoke or full mode, reports per-metric medians + IQR with
    an environment fingerprint, and — with ``--check`` — compares against
    the committed baselines with noise-tolerant thresholds (see
    ``docs/benchmarking.md``).
    """
    harness = _load_harness()
    mode = "smoke" if smoke else "full"
    # the check must compare against the PREVIOUSLY committed baseline,
    # loaded before the suite runs (fail fast: a missing baseline should
    # not cost minutes of benchmarking first) and before
    # --update-baselines overwrites it — otherwise `--update-baselines
    # --check` would compare the run against itself and bless any
    # regression
    try:
        prior_baseline = (
            harness.load_baseline(mode, baseline_dir=baseline_dir)
            if check
            else None
        )
        if check and prior_baseline is None and not update_baselines:
            click.echo(
                f"[pathway_tpu] no committed baseline for mode {mode!r} — "
                "run `pathway_tpu bench --update-baselines` first",
                err=True,
            )
            sys.exit(2)
        results = harness.run_suite(
            mode=mode, reps=reps, only=list(only) or None, echo=click.echo
        )
        if json_path:
            harness.write_results(results, json_path)
            click.echo(
                f"[pathway_tpu] results written to {json_path}", err=True
            )
        # the regression check runs BEFORE any baseline/RESULTS update: a
        # failing check must leave the committed files untouched, or a
        # simple re-run of the same command would report OK against the
        # freshly blessed regression
        report = (
            harness.compare(results, prior_baseline)
            if check and prior_baseline is not None
            else None
        )
        if report is not None and not report["ok"]:
            click.echo(harness.render_report(report))
            click.echo(
                "[pathway_tpu] regression detected — baseline/RESULTS "
                "updates skipped (fix or re-anchor deliberately)",
                err=True,
            )
            sys.exit(1)
        if update_baselines:
            path = harness.update_baseline(results, baseline_dir=baseline_dir)
            click.echo(f"[pathway_tpu] baseline written to {path}", err=True)
        if update_results:
            path = harness.update_results_md(results)
            click.echo(
                f"[pathway_tpu] results table updated in {path}", err=True
            )
    except harness.HarnessError as exc:
        raise click.ClickException(str(exc)) from exc
    if not check:
        sys.exit(0)
    if report is None:
        # bootstrap: no prior baseline existed; this run just created
        # the first one, so there is nothing to regress against
        click.echo(
            "[pathway_tpu] bench check: OK (bootstrap — baseline "
            "created by this run; future runs check against it)"
        )
        sys.exit(0)
    click.echo(harness.render_report(report))
    sys.exit(0)


@cli.command(name="spawn-from-env")
def spawn_from_env():
    """Re-exec ``spawn`` with arguments from PATHWAY_SPAWN_ARGS."""
    from pathway_tpu.internals.config import env_str

    spawn_args = env_str("PATHWAY_SPAWN_ARGS")
    if spawn_args is None:
        click.echo("PATHWAY_SPAWN_ARGS variable is unspecified, exiting...", err=True)
        return
    os.execl(
        sys.executable, sys.executable, "-m", "pathway_tpu", "spawn", *spawn_args.split()
    )


@cli.group()
def airbyte() -> None:
    pass


@airbyte.command(name="create-source")
@click.argument("connection")
@click.option("--image", default="airbyte/source-faker:0.1.4", help="public Airbyte source Docker image")
def create_source(connection, image):
    """Scaffold an Airbyte connection config (requires docker at runtime)."""
    from pathway_tpu.io.airbyte import write_connection_scaffold

    path = write_connection_scaffold(connection, image)
    click.echo(f"Connection `{connection}` with source `{image}` created at {path}")


def main() -> NoReturn:
    cli.main()


if __name__ == "__main__":
    main()
