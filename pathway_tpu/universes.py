"""``import pathway_tpu.universes`` — module-path parity with the
reference's ``pathway/universes.py``."""

from pathway_tpu.internals.universes import *  # noqa: F401,F403
