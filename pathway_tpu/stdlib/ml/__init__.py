"""stdlib.ml (parity: stdlib/ml/): KNN index, classifiers, smart_table_ops, hmm, datasets."""

from pathway_tpu.stdlib.ml import classifiers, hmm, index, smart_table_ops

__all__ = ["classifiers", "hmm", "index", "smart_table_ops"]
