"""Fuzzy-join helpers (parity: stdlib/ml/smart_table_ops.py).

Provides ``fuzzy_match_tables`` — approximate matching of rows between two
tables by token overlap scoring.
"""

from __future__ import annotations

import re
from collections import defaultdict

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import left as lp, right as rp, this

_WORD = re.compile(r"\w+")


def _tokens(s) -> tuple:
    return tuple(sorted({w.lower() for w in _WORD.findall(str(s or ""))}))


def fuzzy_match_tables(
    left: Table,
    right: Table,
    *,
    left_column: ColumnReference | None = None,
    right_column: ColumnReference | None = None,
) -> Table:
    """Match rows by shared tokens; returns (left, right, weight)."""
    lcol = left_column or ColumnReference(left, left.column_names()[0])
    rcol = right_column or ColumnReference(right, right.column_names()[0])
    l_tok = left.select(_pw_tok=ApplyExpression(_tokens, None, lcol))
    r_tok = right.select(_pw_tok=ApplyExpression(_tokens, None, rcol))
    l_flat = l_tok.flatten(ColumnReference(this, "_pw_tok"), origin_id="_pw_lid")
    r_flat = r_tok.flatten(ColumnReference(this, "_pw_tok"), origin_id="_pw_rid")
    pairs = l_flat.join(
        r_flat, ColumnReference(lp, "_pw_tok") == ColumnReference(rp, "_pw_tok")
    ).select(
        left_id=ColumnReference(lp, "_pw_lid"),
        right_id=ColumnReference(rp, "_pw_rid"),
    )
    weights = pairs.groupby(this.left_id, this.right_id).reduce(
        left=this.left_id, right=this.right_id, weight=reducers.count()
    )
    return weights


__all__ = ["fuzzy_match_tables"]
