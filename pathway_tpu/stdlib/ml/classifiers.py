"""Simple classifiers over indexes (parity: stdlib/ml/classifiers/).

``knn_lsh_classifier_train`` / ``classify`` — majority vote over LSH KNN.
"""

from __future__ import annotations

from collections import Counter

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.ml.index import KNNIndex


def knn_lsh_classifier_train(
    data: Table, L: int = 20, type: str = "euclidean", **kwargs
):
    """Returns a classify(labels, queries, k) callable over the trained index."""
    n_dimensions = kwargs.get("d", kwargs.get("n_dimensions", 128))
    index = KNNIndex(
        ColumnReference(data, "data"), data, n_dimensions=n_dimensions,
        distance_type=type,
    )

    def classify(labels: Table, queries: Table, k: int = 3) -> Table:
        labeled = data.with_columns(label=labels.label)
        idx = KNNIndex(
            ColumnReference(labeled, "data"),
            labeled,
            n_dimensions=n_dimensions,
            distance_type=type,
        )
        matches = idx.get_nearest_items(ColumnReference(queries, "data"), k=k)

        def majority(lbls):
            if not lbls:
                return None
            return Counter(lbls).most_common(1)[0][0]

        return matches.select(
            predicted_label=ApplyExpression(majority, None, ColumnReference(this, "label"))
        )

    return classify


__all__ = ["knn_lsh_classifier_train"]
