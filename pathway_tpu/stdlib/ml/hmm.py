"""Hidden Markov Model decoding as an incremental reducer.

Parity target: ``python/pathway/stdlib/ml/hmm.py`` —
``create_hmm_reducer(graph, beam_size, num_results_kept)`` builds an
accumulator for ``pw.reducers.udf_reducer`` that maintains the Viterbi
decoding of a growing observation sequence; each new observation refines
the most-likely state path, emitting retraction + new path per step.

Design difference: the reference replays a deque of observations through
a forward Viterbi pass.  Here the accumulator is a true semigroup — it
stores, per (entry-state, exit-state) pair, the best log-probability
path *through its span of observations* (min-plus matrix form), so
``update`` composes two spans associatively via the transition edges.
That keeps the reducer correct under any update order and maps the
per-pair maximization onto dense array ops.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.reducers import BaseCustomAccumulator


def create_hmm_reducer(
    graph: Any, beam_size: int | None = None, num_results_kept: int | None = None
):
    """Reducer decoding an HMM; see reference docstring for the contract.

    ``graph`` is a ``networkx.DiGraph``: nodes carry
    ``calc_emission_log_ppb(observation) -> float``, edges carry
    ``log_transition_ppb``, ``graph.graph["start_nodes"]`` lists initial
    states.
    """
    states = list(graph.nodes)
    start_nodes = list(graph.graph.get("start_nodes", states))
    emission = {s: graph.nodes[s]["calc_emission_log_ppb"] for s in states}
    transition = {
        (u, v): data["log_transition_ppb"] for u, v, data in graph.edges(data=True)
    }

    class HmmAccumulator(BaseCustomAccumulator):
        """best[(entry, exit)] = (log_ppb, path tuple) over the span."""

        __slots__ = ("best",)

        def __init__(self, best: dict):
            self.best = best

        @classmethod
        def from_row(cls, row):
            (observation,) = row
            best = {}
            for s in states:
                lp = emission[s](observation)
                if lp is not None:
                    best[(s, s)] = (float(lp), (s,))
            return cls(best)

        def update(self, other: "HmmAccumulator") -> None:
            combined: dict = {}
            for (i, j), (lp_left, path_left) in self.best.items():
                for (k, l), (lp_right, path_right) in other.best.items():
                    t = transition.get((j, k))
                    if t is None:
                        continue
                    score = lp_left + t + lp_right
                    cur = combined.get((i, l))
                    if cur is None or score > cur[0]:
                        combined[(i, l)] = (score, path_left + path_right)
            self.best = _prune(combined)

        def compute_result(self) -> tuple:
            candidates = [
                entry
                for (i, _j), entry in self.best.items()
                if i in start_nodes
            ]
            if not candidates:
                return ()
            _, path = max(candidates, key=lambda e: e[0])
            if num_results_kept is not None:
                path = path[-num_results_kept:]
            return path

    def _prune(best: dict) -> dict:
        if beam_size is None:
            return best
        # beam over exit states: keep the beam_size best exits (the states
        # a longer decoding could continue from)
        by_exit: dict = {}
        for (i, j), entry in best.items():
            cur = by_exit.get(j)
            if cur is None or entry[0] > cur[0]:
                by_exit[j] = entry
        kept_exits = {
            j
            for j, _ in sorted(
                by_exit.items(), key=lambda e: e[1][0], reverse=True
            )[:beam_size]
        }
        return {k: v for k, v in best.items() if k[1] in kept_exits}

    HmmAccumulator.__name__ = "hmm"
    return HmmAccumulator
