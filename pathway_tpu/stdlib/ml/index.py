"""Classic KNNIndex facade (parity: stdlib/ml/index.py:9-194).

Wraps stdlib.indexing; kept for API compatibility with the reference's
``pw.ml.index.KNNIndex`` used by the legacy VectorStoreServer path.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    DistanceMetric,
    LshKnn,
)


class KNNIndex:
    """K-nearest-neighbours index over an embedding column."""

    def __init__(
        self,
        data_embedding: ColumnReference,
        data: Table,
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: ColumnReference | None = None,
    ):
        metric = (
            DistanceMetric.L2SQ if distance_type == "euclidean" else DistanceMetric.COS
        )
        inner = BruteForceKnn(
            data_embedding, metadata, dimensions=n_dimensions, metric=metric
        )
        self._index = DataIndex(data, inner)
        self._data = data

    def get_nearest_items(
        self,
        query_embedding: ColumnReference,
        k: int | ColumnReference = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnReference | None = None,
    ) -> Table:
        result = self._index.query(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
        if not with_distances and "_pw_index_reply_score" in result.column_names():
            result = result.without("_pw_index_reply_score")
        else:
            result = result.rename_columns(dist=this._pw_index_reply_score)
        return result

    def get_nearest_items_asof_now(
        self,
        query_embedding: ColumnReference,
        k: int | ColumnReference = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: ColumnReference | None = None,
    ) -> Table:
        result = self._index.query_as_of_now(
            query_embedding,
            number_of_matches=k,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )
        if not with_distances and "_pw_index_reply_score" in result.column_names():
            result = result.without("_pw_index_reply_score")
        else:
            result = result.rename_columns(dist=this._pw_index_reply_score)
        return result


__all__ = ["KNNIndex", "DistanceMetric"]
