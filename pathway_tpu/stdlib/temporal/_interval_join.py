"""Interval joins (parity: stdlib/temporal/_interval_join.py:577-1404).

``interval_join(left, right, left_time, right_time, interval(a, b), *on)``
pairs rows with ``a <= right_time - left_time <= b`` and equal on-keys.
Built from the incremental equi-join on the on-keys plus an interval filter;
outer modes add unmatched rows via incremental anti-join (difference on
matched key sets).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnExpression, ColumnReference
from pathway_tpu.internals.table import JoinMode, JoinResult, Table
from pathway_tpu.internals.thisclass import ThisPlaceholder, left as left_ph, right as right_ph, this


@dataclasses.dataclass(frozen=True)
class Interval:
    lower_bound: Any
    upper_bound: Any


def interval(lower_bound, upper_bound) -> Interval:
    return Interval(lower_bound, upper_bound)


class IntervalJoinResult:
    def __init__(self, left_t, right_t, left_time, right_time, iv, on, mode):
        self._left = left_t
        self._right = right_t
        self._left_time = left_time
        self._right_time = right_time
        self._interval = iv
        self._mode = mode
        self._on = on

    def select(self, *args, **kwargs) -> Table:
        exprs: dict[str, Any] = {}
        for a in args:
            if isinstance(a, ColumnReference):
                exprs[a.name] = a
            else:
                raise ValueError("positional select args must be column refs")
        exprs.update(kwargs)

        lt = self._left_time._substitute({id(this): self._left, id(left_ph): self._left})
        rt = self._right_time._substitute({id(this): self._right, id(right_ph): self._right})
        iv = self._interval

        # inner pairs via equi-join + interval filter
        jr = JoinResult(self._left, self._right, self._on, mode=JoinMode.INNER)
        lt_j = self._left_time._substitute({id(this): left_ph, id(left_ph): left_ph})
        rt_j = self._right_time._substitute({id(this): right_ph, id(right_ph): right_ph})
        # rebind refs of the original tables onto left/right placeholders
        lt_j = _rebind(lt, self._left, "left")
        rt_j = _rebind(rt, self._right, "right")
        diff_e = rt_j - lt_j
        cond = (diff_e >= iv.lower_bound) & (diff_e <= iv.upper_bound)
        sel = dict(exprs)
        sel["_pw_in_interval"] = cond
        inner = jr.select(**sel)
        inner = inner.filter(ColumnReference(this, "_pw_in_interval")).without(
            "_pw_in_interval"
        )
        if self._mode == JoinMode.INNER:
            return inner

        # outer parts: rows with no in-interval partner get None-padded output
        results = [inner]
        if self._mode in (JoinMode.LEFT, JoinMode.OUTER):
            results.append(self._unmatched_side(exprs, side="left", jr_mode=jr))
        if self._mode in (JoinMode.RIGHT, JoinMode.OUTER):
            results.append(self._unmatched_side(exprs, side="right", jr_mode=jr))
        # the three parts keep their source tables' row keys, which can
        # collide across sides — reindex while concatenating
        return results[0].concat_reindex(*results[1:])

    def _unmatched_side(self, exprs, side: str, jr_mode) -> Table:
        """Rows of one side with no interval match, None-padded."""
        base = self._left if side == "left" else self._right
        other = self._right if side == "left" else self._left
        # matched ids of this side
        jr = JoinResult(self._left, self._right, self._on, mode=JoinMode.INNER)
        lt_j = _rebind(
            self._left_time._substitute({id(this): self._left, id(left_ph): self._left}),
            self._left,
            "left",
        )
        rt_j = _rebind(
            self._right_time._substitute({id(this): self._right, id(right_ph): self._right}),
            self._right,
            "right",
        )
        diff_e = rt_j - lt_j
        iv = self._interval
        cond = (diff_e >= iv.lower_bound) & (diff_e <= iv.upper_bound)
        side_id = (
            ColumnReference(left_ph, "id") if side == "left" else ColumnReference(right_ph, "id")
        )
        matched_pairs = jr.select(_pw_matched_id=side_id, _pw_ok=cond)
        matched_pairs = matched_pairs.filter(ColumnReference(this, "_pw_ok"))
        matched_ids = matched_pairs.groupby(
            ColumnReference(this, "_pw_matched_id")
        ).reduce(_pw_matched_id=ColumnReference(this, "_pw_matched_id"))
        matched_keyed = matched_ids.with_id(ColumnReference(this, "_pw_matched_id"))
        unmatched = base.difference(matched_keyed)
        # project expressions with other-side references → None
        sel = {}
        for n, e in exprs.items():
            sel[n] = _null_other_side(expr_mod._wrap(e), other, side)
        return unmatched.select(**sel)


def _rebind(e: ColumnExpression, table: Table, side: str) -> ColumnExpression:
    ph = left_ph if side == "left" else right_ph

    def walk(x):
        if isinstance(x, ColumnReference):
            if x.table is table:
                return ColumnReference(ph, x.name)
            return x
        new = x._substitute({})
        _walk_children(new, walk)
        return new

    return walk(e)


def _null_other_side(e: ColumnExpression, other: Table, keep_side: str) -> ColumnExpression:
    keep_ph = left_ph if keep_side == "left" else right_ph
    drop_ph = right_ph if keep_side == "left" else left_ph

    def walk(x):
        if isinstance(x, ColumnReference):
            if x.table is other or (
                isinstance(x.table, ThisPlaceholder) and x.table._kind == getattr(drop_ph, "_kind")
            ):
                return expr_mod.ColumnConstExpression(None)
            if isinstance(x.table, ThisPlaceholder) and x.table._kind == getattr(keep_ph, "_kind"):
                return ColumnReference(this, x.name)
            if x.table is not other and isinstance(x.table, Table):
                return ColumnReference(this, x.name)
            return x
        new = x._substitute({})
        _walk_children(new, walk)
        return new

    return walk(e)


def _walk_children(e, fn):
    for attr in getattr(e, "__slots__", ()):
        try:
            v = getattr(e, attr)
        except AttributeError:
            continue
        if isinstance(v, ColumnReference):
            object.__setattr__(e, attr, fn(v))
        elif isinstance(v, ColumnExpression):
            _walk_children(v, fn)
        elif isinstance(v, tuple) and any(isinstance(x, ColumnExpression) for x in v):
            object.__setattr__(
                e,
                attr,
                tuple(
                    fn(x)
                    if isinstance(x, ColumnReference)
                    else x
                    for x in v
                ),
            )
        elif isinstance(v, dict):
            for k2, x in list(v.items()):
                if isinstance(x, ColumnReference):
                    v[k2] = fn(x)
                elif isinstance(x, ColumnExpression):
                    _walk_children(x, fn)


def interval_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    iv: Interval,
    *on,
    how: JoinMode = JoinMode.INNER,
    behavior=None,
) -> IntervalJoinResult:
    r"""``pw.temporal.interval_join`` (reference _interval_join.py:577).

    Example:

    >>> import pathway_tpu as pw
    >>> a = pw.debug.table_from_markdown('t | v\n1 | x\n5 | y')
    >>> b = pw.debug.table_from_markdown('t | w\n2 | p\n9 | q')
    >>> r = pw.temporal.interval_join(
    ...     a, b, a.t, b.t, pw.temporal.interval(-1, 1)
    ... ).select(a.v, b.w)
    >>> pw.debug.compute_and_print(r, include_id=False)
    v | w
    x | p
    """
    return IntervalJoinResult(self, other, self_time, other_time, iv, on, how)


def interval_join_inner(self, other, self_time, other_time, iv, *on, **kw):
    kw.pop("how", None)
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinMode.INNER, **kw)


def interval_join_left(self, other, self_time, other_time, iv, *on, **kw):
    kw.pop("how", None)
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinMode.LEFT, **kw)


def interval_join_right(self, other, self_time, other_time, iv, *on, **kw):
    kw.pop("how", None)
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinMode.RIGHT, **kw)


def interval_join_outer(self, other, self_time, other_time, iv, *on, **kw):
    kw.pop("how", None)
    return interval_join(self, other, self_time, other_time, iv, *on, how=JoinMode.OUTER, **kw)
