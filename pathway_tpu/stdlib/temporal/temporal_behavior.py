"""Temporal behaviors (parity: stdlib/temporal/temporal_behavior.py:29-83).

Behaviors are lowered onto the engine's buffer/forget/freeze operators
(``time_column.rs`` analogs in engine/dataflow.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any


class Behavior:
    pass


@dataclasses.dataclass
class CommonBehavior(Behavior):
    """delay: hold results until watermark passes start+delay;
    cutoff: ignore data later than end+cutoff; keep_results: retain closed
    windows."""

    delay: Any = None
    cutoff: Any = None
    keep_results: bool = True


def common_behavior(delay=None, cutoff=None, keep_results: bool = True) -> CommonBehavior:
    return CommonBehavior(delay, cutoff, keep_results)


@dataclasses.dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Any = None


def exactly_once_behavior(shift=None) -> ExactlyOnceBehavior:
    return ExactlyOnceBehavior(shift)
