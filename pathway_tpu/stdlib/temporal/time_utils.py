"""Temporal time utilities: a live UTC clock stream and inactivity alerts.

Parity target: ``python/pathway/stdlib/temporal/time_utils.py``
(``utc_now`` clock source, ``inactivity_detection`` alert pattern).
"""

from __future__ import annotations

import datetime
import time
from functools import cache

import pathway_tpu as pw
from pathway_tpu import io


class TimestampSchema(pw.Schema):
    timestamp_utc: pw.DateTimeUtc


class TimestampSubject(io.python.ConnectorSubject):
    """Emits the current UTC time every ``refresh_rate`` (never finishes)."""

    def __init__(self, refresh_rate: datetime.timedelta) -> None:
        super().__init__()
        self._refresh_rate = refresh_rate

    def run(self) -> None:
        while True:
            now_utc = datetime.datetime.now(datetime.timezone.utc)
            self.next(timestamp_utc=now_utc)
            self.commit()
            time.sleep(self._refresh_rate.total_seconds())


@cache
def utc_now(refresh_rate: datetime.timedelta = datetime.timedelta(seconds=60)):
    """A continuously updating stream of the current UTC time (cached per
    refresh rate, like the reference — one clock per rate per process)."""
    return io.python.read(
        TimestampSubject(refresh_rate=refresh_rate),
        schema=TimestampSchema,
    )


def inactivity_detection(
    event_time_column,
    allowed_inactivity_period,
    refresh_rate: datetime.timedelta = datetime.timedelta(seconds=1),
    instance=None,
):
    """(inactivities, resumed_activities) alert tables for a stream whose
    ``event_time_column`` carries UTC timestamps: an inactivity row appears
    when no event lands within ``allowed_inactivity_period``; a resumed row
    carries the first event after each gap.  Assumes event timestamps track
    current UTC and system latency << the allowed period (reference
    time_utils.py:52)."""
    events_t = event_time_column.table.select(
        t=event_time_column, instance=instance
    )

    now_t = utc_now(refresh_rate=refresh_rate)
    latest_t = (
        events_t.groupby(pw.this.instance)
        .reduce(pw.this.instance, latest_t=pw.reducers.max(pw.this.t))
        .filter(
            pw.this.latest_t > datetime.datetime.now(datetime.timezone.utc)
        )  # avoid alerts while backfilling history
    )
    inactivities = (
        now_t.asof_now_join(latest_t)
        .select(pw.left.timestamp_utc, pw.right.instance, pw.right.latest_t)
        .filter(pw.this.latest_t + allowed_inactivity_period < pw.this.timestamp_utc)
        .groupby(pw.this.latest_t, pw.this.instance)
        .reduce(pw.this.latest_t, pw.this.instance)
        .select(instance=pw.this.instance, inactive_t=pw.this.latest_t)
    )

    latest_inactivity = inactivities.groupby(pw.this.instance).reduce(
        pw.this.instance, inactive_t=pw.reducers.latest(pw.this.inactive_t)
    )
    resumed_activities = (
        events_t.asof_now_join(
            latest_inactivity, events_t.instance == latest_inactivity.instance
        )
        .select(pw.left.t, pw.left.instance, pw.right.inactive_t)
        .groupby(pw.this.inactive_t, pw.this.instance)
        .reduce(pw.this.instance, resumed_t=pw.reducers.min(pw.this.t))
    )
    if instance is None:
        inactivities = inactivities.without(pw.this.instance)
        resumed_activities = resumed_activities.without(pw.this.instance)
    return inactivities, resumed_activities
