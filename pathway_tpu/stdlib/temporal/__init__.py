"""Event-time temporal operations.

Parity target: ``/root/reference/python/pathway/stdlib/temporal/`` (5,650 LoC):
windows (tumbling/sliding/session/intervals_over) + ``windowby``, asof joins,
asof-now joins, interval joins, window joins, and temporal behaviors.
"""

from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    CommonBehavior,
    ExactlyOnceBehavior,
    common_behavior,
    exactly_once_behavior,
)
from pathway_tpu.stdlib.temporal._window import (
    Window,
    intervals_over,
    session,
    sliding,
    tumbling,
    windowby,
)
from pathway_tpu.stdlib.temporal._asof_join import (
    AsofJoinResult,
    Direction,
    asof_join,
    asof_join_left,
    asof_join_outer,
    asof_join_right,
)
from pathway_tpu.stdlib.temporal._asof_now_join import (
    AsofNowJoinResult,
    asof_now_join,
    asof_now_join_inner,
    asof_now_join_left,
)
from pathway_tpu.stdlib.temporal._interval_join import (
    Interval,
    IntervalJoinResult,
    interval,
    interval_join,
    interval_join_inner,
    interval_join_left,
    interval_join_outer,
    interval_join_right,
)
from pathway_tpu.stdlib.temporal._window_join import (
    WindowJoinResult,
    window_join,
    window_join_inner,
    window_join_left,
    window_join_outer,
    window_join_right,
)

__all__ = [
    "AsofNowJoinResult",
    "inactivity_detection",
    "utc_now",
    "Behavior",
    "CommonBehavior",
    "ExactlyOnceBehavior",
    "common_behavior",
    "exactly_once_behavior",
    "Window",
    "tumbling",
    "sliding",
    "session",
    "intervals_over",
    "windowby",
    "AsofJoinResult",
    "Direction",
    "asof_join",
    "asof_join_left",
    "asof_join_right",
    "asof_join_outer",
    "asof_now_join",
    "asof_now_join_inner",
    "asof_now_join_left",
    "Interval",
    "IntervalJoinResult",
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_right",
    "interval_join_outer",
    "WindowJoinResult",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_right",
    "window_join_outer",
]

from pathway_tpu.stdlib.temporal.time_utils import (
    TimestampSchema,
    TimestampSubject,
    inactivity_detection,
    utc_now,
)
