"""Windows + ``windowby`` (parity: stdlib/temporal/_window.py:588-855).

Window assignment is a flatten (each row → its window instances) followed by
an incremental groupby on ``(instance, window_start, window_end)``; session
windows merge chains of rows within ``max_gap`` per instance (recomputed per
touched instance per epoch — the reference's session logic in
``time_column.rs`` is likewise instance-scoped).
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import GroupedTable, Table
from pathway_tpu.internals.thisclass import this
from pathway_tpu.stdlib.temporal.temporal_behavior import (
    Behavior,
    CommonBehavior,
    ExactlyOnceBehavior,
)


class Window:
    def _assign(self, t: Any) -> list[tuple[Any, Any]]:
        """Return the list of (window_start, window_end) containing time t."""
        raise NotImplementedError


def _zero_like(duration):
    if isinstance(duration, datetime.timedelta):
        return datetime.timedelta(0)
    return 0


@dataclasses.dataclass(frozen=True)
class TumblingWindow(Window):
    duration: Any
    origin: Any = None
    shift: Any = None

    def _assign(self, t):
        origin = self.origin
        if origin is None:
            origin = _zero_like(self.duration) if not isinstance(t, datetime.datetime) else datetime.datetime(1970, 1, 1, tzinfo=t.tzinfo)
        offset = t - origin
        n = offset // self.duration
        start = origin + n * self.duration
        if start > t:  # floor for negatives with timedelta arithmetic
            start = start - self.duration
        return [(start, start + self.duration)]


@dataclasses.dataclass(frozen=True)
class SlidingWindow(Window):
    hop: Any
    duration: Any
    origin: Any = None

    def _assign(self, t):
        origin = self.origin
        if origin is None:
            origin = _zero_like(self.hop) if not isinstance(t, datetime.datetime) else datetime.datetime(1970, 1, 1, tzinfo=t.tzinfo)
        out = []
        # windows [origin + k*hop, origin + k*hop + duration) containing t
        offset = t - origin
        k_max = offset // self.hop
        k = k_max
        while True:
            start = origin + k * self.hop
            if start > t:
                k -= 1
                continue
            if start + self.duration <= t:
                break
            out.append((start, start + self.duration))
            k -= 1
        out.reverse()
        return out


@dataclasses.dataclass(frozen=True)
class SessionWindow(Window):
    predicate: Callable[[Any, Any], bool] | None = None
    max_gap: Any = None

    def merges(self, a, b) -> bool:
        if self.predicate is not None:
            return bool(self.predicate(a, b))
        return (b - a) <= self.max_gap


@dataclasses.dataclass(frozen=True)
class IntervalsOverWindow(Window):
    at: Any  # ColumnReference into a times table
    lower_bound: Any = None
    upper_bound: Any = None
    is_outer: bool = True


def tumbling(duration, origin=None, shift=None) -> TumblingWindow:
    r"""Fixed-size non-overlapping event-time windows.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('at | v\n1 | 10\n3 | 20\n7 | 30')
    >>> r = t.windowby(pw.this.at, window=pw.temporal.tumbling(duration=5)).reduce(
    ...     start=pw.this._pw_window_start, total=pw.reducers.sum(pw.this.v)
    ... )
    >>> pw.debug.compute_and_print(r, include_id=False)
    start | total
    0     | 30
    5     | 30
    """
    if shift is not None:
        return SlidingWindow(hop=shift, duration=duration, origin=origin)
    return TumblingWindow(duration=duration, origin=origin)


def sliding(hop, duration=None, ratio=None, origin=None) -> SlidingWindow:
    r"""Overlapping windows of ``duration`` starting every ``hop``.

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('at\n4\n6')
    >>> r = t.windowby(pw.this.at, window=pw.temporal.sliding(hop=5, duration=10)).reduce(
    ...     start=pw.this._pw_window_start, n=pw.reducers.count()
    ... )
    >>> pw.debug.compute_and_print(r, include_id=False)
    start | n
    -5    | 1
    0     | 2
    5     | 1
    """
    if duration is None and ratio is not None:
        duration = hop * ratio
    return SlidingWindow(hop=hop, duration=duration, origin=origin)


def session(*, predicate=None, max_gap=None) -> SessionWindow:
    r"""Windows that merge events closer than ``max_gap`` (or by ``predicate``).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('at\n1\n2\n10')
    >>> r = t.windowby(pw.this.at, window=pw.temporal.session(max_gap=3)).reduce(
    ...     n=pw.reducers.count()
    ... )
    >>> pw.debug.compute_and_print(r, include_id=False)
    n
    1
    2
    """
    if (predicate is None) == (max_gap is None):
        raise ValueError("session window needs exactly one of predicate/max_gap")
    return SessionWindow(predicate=predicate, max_gap=max_gap)


def intervals_over(*, at, lower_bound=None, upper_bound=None, is_outer: bool = True) -> IntervalsOverWindow:
    return IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


class WindowGroupedTable:
    """Result of windowby; reduce() closes over (instance, start, end) groups."""

    def __init__(self, assigned: Table, has_instance: bool, outer_info=None):
        self._assigned = assigned
        self._has_instance = has_instance
        # intervals_over(is_outer=True): (times_table, lb, ub) — empty
        # intervals still emit their at-point with None reduced values
        self._outer_info = outer_info

    def reduce(self, *args, **kwargs) -> Table:
        grouping = [
            ColumnReference(this, "_pw_window"),
            ColumnReference(this, "_pw_window_start"),
            ColumnReference(this, "_pw_window_end"),
        ]
        if self._has_instance:
            grouping.append(ColumnReference(this, "_pw_instance"))
        inner = self._assigned.groupby(*grouping).reduce(*args, **kwargs)
        if self._outer_info is None:
            return inner
        return self._pad_empty_intervals(inner, args, kwargs)

    def _pad_empty_intervals(self, inner: Table, args, kwargs) -> Table:
        """Anchors with no rows in their interval appear with None in every
        non-group column (reference intervals_over is_outer=True)."""
        times_table, lb, ub = self._outer_info
        at = ColumnReference(this, "_pw_at")
        pad = times_table.select(
            _pw_window=at,
            _pw_window_start=(at + lb) if lb is not None else at,
            _pw_window_end=(at + ub) if ub is not None else at,
        )
        # key pads exactly like the groupby keys its outputs: the hash of
        # the grouping tuple, in grouping order
        pad = pad.with_id_from(
            ColumnReference(this, "_pw_window"),
            ColumnReference(this, "_pw_window_start"),
            ColumnReference(this, "_pw_window_end"),
        )
        named: dict[str, Any] = {}
        for a in args:
            named[a.name] = a
        named.update(kwargs)
        out_cols: dict[str, Any] = {}
        for name, e in named.items():
            if isinstance(e, ColumnReference) and e.name in (
                "_pw_window",
                "_pw_window_start",
                "_pw_window_end",
            ):
                out_cols[name] = ColumnReference(this, e.name)
            else:
                out_cols[name] = expr_mod.ColumnConstExpression(None)
        padded = pad.select(**out_cols)
        missing = padded.difference(inner)
        return inner.concat(missing)


def windowby(
    table: Table,
    time_expr,
    *,
    window: Window,
    behavior: Behavior | None = None,
    instance=None,
    origin=None,
) -> WindowGroupedTable:
    if isinstance(window, SessionWindow):
        assigned = _assign_sessions(table, time_expr, window, instance)
        if behavior is not None:
            assigned = _apply_behavior(assigned, behavior)
    elif isinstance(window, IntervalsOverWindow):
        times_table = window.at.table.select(_pw_at=window.at)
        assigned = _assign_intervals_over(
            table, time_expr, window, instance, times_table
        )
        if behavior is not None:
            assigned = _apply_behavior(assigned, behavior)
        # outer padding caveats: with instance= the pad keys could not
        # match the (window, ..., instance) group keys (phantom pads for
        # every anchor); with keep_results=False a forgotten window would
        # be resurrected as an empty pad.  Both combinations skip padding.
        forgets = (
            isinstance(behavior, CommonBehavior) and not behavior.keep_results
        )
        if window.is_outer and instance is None and not forgets:
            outer_info = (
                times_table,
                window.lower_bound,
                window.upper_bound,
            )
            return WindowGroupedTable(
                assigned, has_instance=instance is not None,
                outer_info=outer_info,
            )
    else:
        win = window
        if _sliding_vectorizable(table, time_expr, win):
            # duration = m·hop over an int time column: every row is in
            # EXACTLY m windows, so the assignment becomes m fully
            # columnar branches (arithmetic starts, make_tuple windows),
            # each injectively rekeyed (native salted hash) and
            # concatenated — no per-row _assign, no flatten
            origin = 0 if win.origin is None else win.origin
            hop, duration = win.hop, win.duration
            m = duration // hop

            def base_of():
                return ((time_expr - origin) // hop) * hop + origin

            branches = []
            for j in range(m):
                # ascending starts, like _assign's reversed output
                shift = (m - 1 - j) * hop
                start = base_of() - shift
                cols = {
                    "_pw_time": time_expr,
                    "_pw_window_start": start,
                    "_pw_window_end": start + duration,
                    "_pw_window": expr_mod.MakeTupleExpression(
                        start, start + duration
                    ),
                }
                if instance is not None:
                    cols["_pw_instance"] = instance
                b = table.with_columns(**cols)
                if m > 1:  # rekey exists only to keep concat branches disjoint
                    b = b._rekey_salted(j)
                branches.append(b)
            assigned = branches[0].concat(*branches[1:]) if m > 1 else branches[0]
            if behavior is not None:
                assigned = _apply_behavior(assigned, behavior)
            return WindowGroupedTable(assigned, has_instance=instance is not None)
        if _tumbling_vectorizable(table, time_expr, win):
            # tumbling over a non-optional int column assigns EXACTLY one
            # window per row via plain arithmetic: the start/end columns
            # compile onto the columnar path (no per-row _assign call, no
            # flatten), and the multi-key columnar groupby reduces them.
            # Python // floors, matching _assign's floor for negatives.
            origin = win.duration * 0 if win.origin is None else win.origin
            d = win.duration

            def start_of():
                return ((time_expr - origin) // d) * d + origin

            cols = {
                "_pw_time": time_expr,
                "_pw_window_start": start_of(),
                "_pw_window_end": start_of() + d,
                # the window value is the (start, end) pair, as on the
                # flatten path; make_tuple compiles columnar
                "_pw_window": expr_mod.MakeTupleExpression(
                    start_of(), start_of() + d
                ),
            }
            if instance is not None:
                cols["_pw_instance"] = instance
            assigned = table.with_columns(**cols)
            if behavior is not None:
                assigned = _apply_behavior(assigned, behavior)
            return WindowGroupedTable(assigned, has_instance=instance is not None)

        def windows_of(t):
            if t is None:
                return ()
            return tuple(
                (s, e) for (s, e) in win._assign(t)
            )

        with_windows = table.with_columns(
            _pw_windows=ApplyExpression(windows_of, None, time_expr),
            _pw_time=time_expr,
        )
        if instance is not None:
            with_windows = with_windows.with_columns(_pw_instance=instance)
        flat = with_windows.flatten(ColumnReference(this, "_pw_windows"))
        assigned = flat.with_columns(
            _pw_window=ColumnReference(this, "_pw_windows"),
            _pw_window_start=ApplyExpression(
                lambda w: w[0], None, ColumnReference(this, "_pw_windows")
            ),
            _pw_window_end=ApplyExpression(
                lambda w: w[1], None, ColumnReference(this, "_pw_windows")
            ),
        )
        if behavior is not None:
            assigned = _apply_behavior(assigned, behavior)
    return WindowGroupedTable(assigned, has_instance=instance is not None)


def _sliding_vectorizable(table: Table, time_expr, win) -> bool:
    """Sliding fast path: int time column, int hop/duration with duration
    an exact multiple of hop (constant windows-per-row), int origin."""
    if not isinstance(win, SlidingWindow):
        return False
    if not (isinstance(win.hop, int) and isinstance(win.duration, int)):
        return False
    if win.hop <= 0 or win.duration <= 0 or win.duration % win.hop != 0:
        return False
    if win.origin is not None and not isinstance(win.origin, int):
        return False
    return _int_time_column(table, time_expr)


def _int_time_column(table: Table, time_expr) -> bool:
    from pathway_tpu.internals import dtype as dt
    from pathway_tpu.internals.thisclass import ThisPlaceholder

    if not isinstance(time_expr, ColumnReference):
        return False
    tbl = time_expr.table
    if isinstance(tbl, ThisPlaceholder):
        tbl = table
    sch = getattr(tbl, "schema", None)
    col = sch.__columns__.get(time_expr.name) if sch is not None else None
    return col is not None and col.dtype is dt.INT


def _tumbling_vectorizable(table: Table, time_expr, win) -> bool:
    """The arithmetic fast path is exact only for non-optional int time
    columns with int duration/origin (float times keep float // float
    quirks on the row path; None times must drop the row, which the
    windows_of path does and arithmetic cannot)."""
    if not isinstance(win, TumblingWindow):
        return False
    if not isinstance(win.duration, int) or win.duration == 0:
        return False
    if win.origin is not None and not isinstance(win.origin, int):
        return False
    return _int_time_column(table, time_expr)


def _apply_behavior(assigned: Table, behavior: Behavior) -> Table:
    time_col = ColumnReference(this, "_pw_time")
    if isinstance(behavior, CommonBehavior):
        t = assigned
        if behavior.delay is not None:
            t = t._buffer(time_col + behavior.delay, time_col)
        if behavior.cutoff is not None:
            end_col = ColumnReference(this, "_pw_window_end")
            t = t._freeze(end_col + behavior.cutoff, time_col)
            if not behavior.keep_results:
                # closed windows are dropped from the output entirely
                # (reference CommonBehavior keep_results=False: the Forget
                # operator retracts rows once the watermark passes cutoff)
                t = t._forget(end_col + behavior.cutoff, time_col)
        return t
    if isinstance(behavior, ExactlyOnceBehavior):
        end_col = ColumnReference(this, "_pw_window_end")
        shift = behavior.shift
        thr = end_col + shift if shift is not None else end_col
        t = assigned._buffer(thr, time_col)
        t = t._freeze(thr, time_col)
        return t
    return assigned


def _sessions_of_loop(win: SessionWindow, times_tuple) -> tuple:
    """Reference per-pair merge loop — the semantics oracle for the
    vectorized gap path, and the only option for custom predicates."""
    times = sorted(times_tuple)
    out = []
    cur_start = None
    prev = None
    for t in times:
        if cur_start is None:
            cur_start = t
        elif not win.merges(prev, t):
            out.append((cur_start, prev))
            cur_start = t
        prev = t
    if cur_start is not None:
        out.append((cur_start, prev))
    return tuple(out)


def _session_gap_vectorizable(table: Table, time_expr, win: SessionWindow) -> bool:
    """Gap-based session fast path: int max_gap over a non-optional int
    time column — the merge test is exact int64 arithmetic.  Float/
    datetime gaps keep the reference loop (Python comparison semantics),
    like the tumbling/sliding gates above."""
    if not isinstance(win.max_gap, int):
        return False
    if not -(2**63) <= win.max_gap < 2**63:
        return False  # bignum gap: numpy comparison would not be exact
    return _int_time_column(table, time_expr)


def _assign_sessions(table: Table, time_expr, window: SessionWindow, instance) -> Table:
    """Sessionization: group rows per instance, merge chains via the window
    predicate, emit (start, end) per session.  Incremental at instance
    granularity via groupby+sorted_tuple then flatten."""
    from pathway_tpu.internals import reducers

    base = table.with_columns(_pw_time=time_expr)
    if instance is not None:
        base = base.with_columns(_pw_instance=instance)
    else:
        base = base.with_columns(_pw_instance=expr_mod.ColumnConstExpression(0))

    from pathway_tpu.internals import vector_compiler as vc

    win = window

    if (
        vc.ENABLED
        and win.predicate is None
        and _session_gap_vectorizable(table, time_expr, win)
    ):
        # gap-based sessions over an int time column: the merge decision
        # is pure arithmetic (gap = t[i] - t[i-1] <= max_gap), so the
        # per-instance chain merge becomes one numpy diff + boundary
        # split instead of a Python loop over every event — the columnar
        # form of the reference's instance-scoped session recompute
        gap = win.max_gap

        def sessions_of(times_tuple):
            import numpy as np

            if not times_tuple:
                return ()
            times = np.sort(np.asarray(times_tuple, dtype=np.int64))
            if int(times[-1]) - int(times[0]) > 2**63 - 1:
                # int64 diff would wrap; the reference loop uses Python
                # bignums and stays exact
                return _sessions_of_loop(win, times_tuple)
            breaks = np.flatnonzero(np.diff(times) > gap)
            starts = times[np.concatenate(([0], breaks + 1))]
            ends = times[np.concatenate((breaks, [times.size - 1]))]
            return tuple(zip(starts.tolist(), ends.tolist()))
    else:
        if vc.ENABLED and win.predicate is not None:
            # a custom merge predicate is opaque Python — it must run
            # per adjacent pair, so this assignment cannot vectorize.
            # Classified under its own reason so `pathway_tpu top` and
            # profiler snapshots attribute the row-speed cost to the
            # predicate, not to a missing fast path.
            vc.note_bail("session", "predicate-merge")
        elif vc.ENABLED:
            # max_gap over a non-int time column (float/datetime):
            # arithmetic exactness isn't guaranteed columnar, keep the
            # reference loop and say why
            vc.note_bail("session", "time-dtype")

        def sessions_of(times_tuple):
            return _sessions_of_loop(win, times_tuple)

    # session boundaries per instance
    sessions = base.groupby(ColumnReference(this, "_pw_instance")).reduce(
        _pw_instance=ColumnReference(this, "_pw_instance"),
        _pw_sessions=ApplyExpression(
            sessions_of, None, reducers.sorted_tuple(ColumnReference(this, "_pw_time"))
        ),
    )
    sess_flat = sessions.flatten(ColumnReference(this, "_pw_sessions"))
    sess_flat = sess_flat.with_columns(
        _pw_window_start=ApplyExpression(
            lambda w: w[0], None, ColumnReference(this, "_pw_sessions")
        ),
        _pw_window_end=ApplyExpression(
            lambda w: w[1], None, ColumnReference(this, "_pw_sessions")
        ),
    )
    # join rows back onto their session: time in [start, end]
    from pathway_tpu.internals.thisclass import left as left_ph, right as right_ph

    jr = base.join(
        sess_flat,
        expr_mod.ColumnBinaryOpExpression(
            "==",
            ColumnReference(left_ph, "_pw_instance"),
            ColumnReference(right_ph, "_pw_instance"),
        ),
    )
    cols = {n: ColumnReference(left_ph, n) for n in table.column_names()}
    cols["_pw_time"] = ColumnReference(left_ph, "_pw_time")
    cols["_pw_instance"] = ColumnReference(left_ph, "_pw_instance")
    cols["_pw_window_start"] = ColumnReference(right_ph, "_pw_window_start")
    cols["_pw_window_end"] = ColumnReference(right_ph, "_pw_window_end")
    cols["_pw_window"] = expr_mod.make_tuple(
        ColumnReference(right_ph, "_pw_window_start"),
        ColumnReference(right_ph, "_pw_window_end"),
    )
    joined = jr.select(**cols)
    return joined.filter(
        (ColumnReference(this, "_pw_time") >= ColumnReference(this, "_pw_window_start"))
        & (ColumnReference(this, "_pw_time") <= ColumnReference(this, "_pw_window_end"))
    )


def _assign_intervals_over(
    table: Table, time_expr, window: IntervalsOverWindow, instance, times_table: Table
) -> Table:
    """intervals_over: windows centered at each value of ``window.at``."""
    from pathway_tpu.internals.thisclass import left as left_ph, right as right_ph

    base = table.with_columns(_pw_time=time_expr)
    if instance is not None:
        base = base.with_columns(_pw_instance=instance)
    else:
        base = base.with_columns(_pw_instance=expr_mod.ColumnConstExpression(0))
    # cross join rows x window anchors (filtered by interval containment)
    jr = base.join(
        times_table,
        expr_mod.ColumnBinaryOpExpression(
            "==",
            expr_mod.ColumnConstExpression(0),
            expr_mod.ColumnConstExpression(0),
        ),
    )
    lb, ub = window.lower_bound, window.upper_bound
    cols = {n: ColumnReference(left_ph, n) for n in table.column_names()}
    cols["_pw_time"] = ColumnReference(left_ph, "_pw_time")
    cols["_pw_instance"] = ColumnReference(left_ph, "_pw_instance")
    cols["_pw_window_start"] = (
        ColumnReference(right_ph, "_pw_at") + lb
        if lb is not None
        else ColumnReference(right_ph, "_pw_at")
    )
    cols["_pw_window_end"] = (
        ColumnReference(right_ph, "_pw_at") + ub
        if ub is not None
        else ColumnReference(right_ph, "_pw_at")
    )
    cols["_pw_window"] = ColumnReference(right_ph, "_pw_at")
    joined = jr.select(**cols)
    return joined.filter(
        (ColumnReference(this, "_pw_time") >= ColumnReference(this, "_pw_window_start"))
        & (ColumnReference(this, "_pw_time") <= ColumnReference(this, "_pw_window_end"))
    )
