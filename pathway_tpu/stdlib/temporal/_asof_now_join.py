"""asof-now joins (parity: stdlib/temporal/_asof_now_join.py).

``asof_now_join`` matches each *arriving* left row against the right side's
current state; results are not revised when the right side later changes —
the query-stream semantics used by the RAG retrieval path (§3.4).
Implemented on a dedicated engine node that indexes the right side but only
reacts to left-side deltas.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any

from pathway_tpu.engine import dataflow as df
from pathway_tpu.engine.types import Error, hash_values, Pointer
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.expression_evaluator import compile_expr
from pathway_tpu.internals.table import (
    JoinMode,
    JoinResult,
    Lowerer,
    RowBinder,
    Table,
    Universe,
    _fetch_chain,
)
from pathway_tpu.internals.thisclass import ThisPlaceholder, left as left_ph, right as right_ph, this


class AsofNowJoinNode(df.Node):
    """Port 0: left (query) stream; port 1: right (data) stream.

    Left inserts are matched against the current right index and the result
    is frozen; later right-side changes do not retract it.  Left deletions
    retract previously emitted results.
    """

    name = "asof_now_join"
    _persist_attrs = ("_right_idx", "_emitted")

    def __init__(self, scope, left_node, right_node, lkey_fn, rkey_fn, out_key_fn, left_outer):
        super().__init__(scope, [left_node, right_node])
        self.lkey_fn = lkey_fn
        self.rkey_fn = rkey_fn
        self.out_key_fn = out_key_fn
        self.left_outer = left_outer
        self._right_idx: dict[Any, dict[int, tuple]] = defaultdict(dict)
        self._emitted: dict[int, list] = {}

    def step(self, time):
        out = []
        # right side first: index updates happen-before matching this epoch
        for rkey, rrow, diff in df.consolidate(self.take_pending(1)):
            jk = self.rkey_fn(rkey, rrow)
            if jk is None:
                continue
            if diff > 0:
                self._right_idx[jk][rkey] = rrow
            else:
                self._right_idx[jk].pop(rkey, None)
                if not self._right_idx[jk]:
                    del self._right_idx[jk]
        for lkey, lrow, diff in df.consolidate(self.take_pending(0)):
            if diff > 0:
                jk = self.lkey_fn(lkey, lrow)
                matches = self._right_idx.get(jk, {}) if jk is not None else {}
                emitted = []
                if matches:
                    for rkey, rrow in matches.items():
                        okey = self.out_key_fn(lkey, rkey)
                        entry = (okey, (lkey, rkey, lrow, rrow), 1)
                        out.append(entry)
                        emitted.append(entry)
                elif self.left_outer:
                    okey = self.out_key_fn(lkey, None)
                    entry = (okey, (lkey, None, lrow, None), 1)
                    out.append(entry)
                    emitted.append(entry)
                self._emitted[lkey] = emitted
            else:
                for okey, row, _ in self._emitted.pop(lkey, []):
                    out.append((okey, row, -1))
        out = df.consolidate(out)
        if self.keep_state:
            self._update_state(out)
        self.send(out, time)


class AsofNowJoinResult(JoinResult):
    """Reuses JoinResult's select/binder machinery over the asof-now node."""

    def _lower_join(self, lowerer: Lowerer):
        lnode = lowerer.node(self._left)
        rnode = lowerer.node(self._right)
        lbinder = RowBinder(lowerer, self._left)
        rbinder = RowBinder(lowerer, self._right)
        l_fns = [compile_expr(e, lbinder) for e in self._left_on]
        r_fns = [compile_expr(e, rbinder) for e in self._right_on]
        lnode = _fetch_chain(lowerer, lnode, lbinder)
        rnode = _fetch_chain(lowerer, rnode, rbinder)

        def guard(fns):
            def f(key, row):
                vals = tuple(fn(key, row) for fn in fns)
                if any(v is None or isinstance(v, Error) for v in vals):
                    return None
                return vals

            return f

        id_param = self._id_param
        left_table = self._left

        def out_key_fn(lkey, rkey):
            if id_param is not None and isinstance(id_param, ColumnReference):
                if id_param.name == "id":
                    src = id_param.table
                    if src is left_table or (
                        isinstance(src, ThisPlaceholder) and src._kind == "left"
                    ):
                        return lkey
            return hash_values(
                [
                    Pointer(lkey) if lkey is not None else None,
                    Pointer(rkey) if rkey is not None else None,
                ]
            )

        return AsofNowJoinNode(
            lowerer.scope,
            lnode,
            rnode,
            guard(l_fns),
            guard(r_fns),
            out_key_fn,
            left_outer=self._mode == JoinMode.LEFT,
        )


def asof_now_join(
    self: Table, other: Table, *on, how: JoinMode = JoinMode.INNER, id=None, **kw
) -> AsofNowJoinResult:
    if how not in (JoinMode.INNER, JoinMode.LEFT):
        raise ValueError("asof_now_join supports INNER and LEFT modes")
    return AsofNowJoinResult(self, other, on, mode=how, id=id)


def asof_now_join_inner(self, other, *on, **kw) -> AsofNowJoinResult:
    kw.pop("how", None)
    return asof_now_join(self, other, *on, how=JoinMode.INNER, **kw)


def asof_now_join_left(self, other, *on, **kw) -> AsofNowJoinResult:
    kw.pop("how", None)
    return asof_now_join(self, other, *on, how=JoinMode.LEFT, **kw)
