"""As-of joins (parity: stdlib/temporal/_asof_join.py:479-1000).

Incremental construction from engine primitives: the right side is folded
per join-key into a sorted tuple of (time, row) entries (an incremental
groupby), the left side left-joins that fold, and per-row binary search
picks the as-of match.  A change on either side retracts and re-emits only
the affected rows — the same net behavior as the reference's dedicated
prev/next pointer machinery (prev_next.rs), chosen here because the fold
keeps per-key state contiguous, which is the layout a future device-side
batch lookup wants.
"""

from __future__ import annotations

import bisect
import enum
from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import (
    ApplyExpression,
    ColumnExpression,
    ColumnReference,
)
from pathway_tpu.internals.table import JoinMode, JoinResult, Table
from pathway_tpu.internals.thisclass import ThisPlaceholder, left as left_ph, right as right_ph, this


class Direction(enum.Enum):
    BACKWARD = 0
    FORWARD = 1
    NEAREST = 2


def _lookup(entries, lt, direction: Direction):
    """entries: sorted tuple of (time, row_tuple); find the as-of entry."""
    if entries is None or len(entries) == 0 or lt is None:
        return None
    times = [e[0] for e in entries]
    if direction is Direction.BACKWARD:
        i = bisect.bisect_right(times, lt) - 1
        return entries[i] if i >= 0 else None
    if direction is Direction.FORWARD:
        i = bisect.bisect_left(times, lt)
        return entries[i] if i < len(entries) else None
    # NEAREST
    i = bisect.bisect_left(times, lt)
    best = None
    for j in (i - 1, i):
        if 0 <= j < len(entries):
            d = abs(entries[j][0] - lt)
            if best is None or d < best[0]:
                best = (d, entries[j])
    return best[1] if best else None


class AsofJoinResult:
    def __init__(
        self,
        left_table: Table,
        right_table: Table,
        left_time,
        right_time,
        on,
        mode: JoinMode,
        defaults: dict | None = None,
        direction: Direction = Direction.BACKWARD,
    ):
        self._left = left_table
        self._orig_left = left_table
        self._right = right_table
        self._mode = mode
        self._defaults = {}
        for k, v in (defaults or {}).items():
            name = k.name if isinstance(k, ColumnReference) else k
            self._defaults[name] = v
        self._direction = direction
        self._left_time = left_time
        self._right_time = right_time
        self._r_names = right_table.column_names()

        # fold the right side per join key
        left_on, right_on = [], []
        for cond in on:
            if not isinstance(cond, expr_mod.ColumnBinaryOpExpression) or cond._op != "==":
                raise ValueError("asof_join conditions must be equalities")
            l_e, r_e = cond._left, cond._right
            if JoinResult._refers(r_e, left_table) or (
                isinstance(r_e, ColumnReference)
                and isinstance(r_e.table, ThisPlaceholder)
                and r_e.table._kind == "left"
            ):
                l_e, r_e = r_e, l_e
            left_on.append(l_e._substitute({id(left_ph): left_table, id(this): left_table}))
            right_on.append(r_e._substitute({id(right_ph): right_table, id(this): right_table}))

        entry_expr = expr_mod.make_tuple(
            right_time._substitute({id(this): right_table, id(right_ph): right_table}),
            expr_mod.make_tuple(*[ColumnReference(this, n) for n in self._r_names]),
        )
        if right_on:
            # grouping by expressions: select them first
            keyed_right = right_table.with_columns(
                **{f"_pw_k{i}": e for i, e in enumerate(right_on)}
            )
            folded = keyed_right.groupby(
                *[ColumnReference(this, f"_pw_k{i}") for i in range(len(right_on))]
            ).reduce(
                **{f"_pw_k{i}": ColumnReference(this, f"_pw_k{i}") for i in range(len(right_on))},
                _pw_entries=reducers.sorted_tuple(entry_expr),
            )
            on_conds = [
                expr_mod.ColumnBinaryOpExpression(
                    "==", left_on[i], ColumnReference(folded, f"_pw_k{i}")
                )
                for i in range(len(left_on))
            ]
            self._joined = JoinResult(left_table, folded, on_conds, mode=JoinMode.LEFT)
            self._folded = folded
        else:
            # no key: fold everything into one group and cross with left
            folded = right_table.reduce(
                _pw_all=expr_mod.ColumnConstExpression(0),
                _pw_entries=reducers.sorted_tuple(entry_expr),
            )
            keyed_left = left_table.with_columns(_pw_all=expr_mod.ColumnConstExpression(0))
            on_conds = [
                expr_mod.ColumnBinaryOpExpression(
                    "==",
                    ColumnReference(keyed_left, "_pw_all"),
                    ColumnReference(folded, "_pw_all"),
                )
            ]
            self._joined = JoinResult(keyed_left, folded, on_conds, mode=JoinMode.LEFT)
            self._left = keyed_left
            self._folded = folded

    def select(self, *args, **kwargs) -> Table:
        exprs: dict[str, Any] = {}
        for a in args:
            exprs[_ref_name(a)] = a
        exprs.update(kwargs)

        direction = self._direction
        defaults = self._defaults
        r_names = self._r_names
        lt_expr = self._left_time._substitute(
            {id(this): self._left, id(left_ph): self._left}
        )

        def fix_left(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ColumnReference):
                if e.table is self._orig_left and e.table is not self._left:
                    return ColumnReference(self._left, e.name)
                return e
            new = e._substitute({})
            _rewrite_children(new, fix_left)
            return new

        lt_expr = fix_left(lt_expr)

        def right_col_expr(name: str) -> ColumnExpression:
            idx = r_names.index(name)
            default = defaults.get(name)

            def extract(entries, lt, _idx=idx, _default=default):
                e = _lookup(entries, lt, direction)
                if e is None:
                    return _default
                return e[1][_idx]

            return ApplyExpression(
                extract,
                None,
                ColumnReference(self._folded, "_pw_entries"),
                lt_expr,
                _propagate_none=False,
            )

        def substitute_right(e: ColumnExpression) -> ColumnExpression:
            if isinstance(e, ColumnReference):
                tbl = e.table
                if tbl is self._right or (
                    isinstance(tbl, ThisPlaceholder) and tbl._kind == "right"
                ):
                    return right_col_expr(e.name)
                if tbl is self._orig_left and tbl is not self._left:
                    # the unkeyed path wraps the left table; refs to the
                    # user's original table must land on the wrapped one
                    return ColumnReference(self._left, e.name)
                return e
            new = e._substitute({})
            _rewrite_children(new, substitute_right)
            return new

        final = {}
        for n, e in exprs.items():
            final[n] = substitute_right(expr_mod._wrap(e))
        result = self._joined.select(**final)
        if self._mode == JoinMode.INNER:

            def found(entries, lt):
                return _lookup(entries, lt, direction) is not None

            matched = self._joined.select(
                **final,
                _pw_found=ApplyExpression(
                    found,
                    None,
                    ColumnReference(self._folded, "_pw_entries"),
                    lt_expr,
                    _propagate_none=False,
                ),
            )
            result = matched.filter(ColumnReference(this, "_pw_found")).without(
                "_pw_found"
            )
        return result


def _ref_name(e) -> str:
    if isinstance(e, ColumnReference):
        return e.name
    raise ValueError("positional args of asof select must be column references")


def _rewrite_children(e, fn):
    for attr in getattr(e, "__slots__", ()):
        try:
            v = getattr(e, attr)
        except AttributeError:
            continue
        if isinstance(v, ColumnReference):
            object.__setattr__(e, attr, fn(v))
        elif isinstance(v, ColumnExpression):
            _rewrite_children(v, fn)
        elif isinstance(v, tuple) and any(isinstance(x, ColumnExpression) for x in v):
            object.__setattr__(
                e,
                attr,
                tuple(fn(x) if isinstance(x, ColumnReference) else (_rewrite_children(x, fn) or x) if isinstance(x, ColumnExpression) else x for x in v),
            )
        elif isinstance(v, dict):
            for k2, x in list(v.items()):
                if isinstance(x, ColumnReference):
                    v[k2] = fn(x)
                elif isinstance(x, ColumnExpression):
                    _rewrite_children(x, fn)


def asof_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    *on,
    how: JoinMode = JoinMode.INNER,
    defaults: dict | None = None,
    direction: Direction = Direction.BACKWARD,
    behavior=None,
) -> AsofJoinResult:
    r"""``pw.temporal.asof_join`` (reference _asof_join.py:479).

    Example:

    >>> import pathway_tpu as pw
    >>> trades = pw.debug.table_from_markdown('t | px\n3 | 100\n7 | 101')
    >>> quotes = pw.debug.table_from_markdown('t | bid\n2 | 99\n6 | 98')
    >>> r = pw.temporal.asof_join(
    ...     trades, quotes, trades.t, quotes.t, how=pw.temporal.Direction.BACKWARD
    ... ).select(trades.px, quotes.bid)
    >>> pw.debug.compute_and_print(r, include_id=False)
    px  | bid
    100 | 99
    101 | 98
    """
    return AsofJoinResult(
        self, other, self_time, other_time, on, mode=how, defaults=defaults, direction=direction
    )


def asof_join_left(self, other, self_time, other_time, *on, **kw) -> AsofJoinResult:
    kw.pop("how", None)
    return asof_join(self, other, self_time, other_time, *on, how=JoinMode.LEFT, **kw)


def asof_join_right(self, other, self_time, other_time, *on, **kw) -> AsofJoinResult:
    kw.pop("how", None)
    res = asof_join(
        other, self, other_time, self_time, *on, how=JoinMode.LEFT, **kw
    )
    res._swapped = True
    return res


def asof_join_outer(self, other, self_time, other_time, *on, **kw) -> AsofJoinResult:
    kw.pop("how", None)
    return asof_join(self, other, self_time, other_time, *on, how=JoinMode.LEFT, **kw)
