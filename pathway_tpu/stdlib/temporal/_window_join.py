"""Window joins (parity: stdlib/temporal/_window_join.py).

Rows of both sides are assigned to windows; pairs sharing a window (and the
on-keys) join.  Composed from window assignment (flatten) + equi-join.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import JoinMode, JoinResult, Table
from pathway_tpu.internals.thisclass import left as left_ph, right as right_ph, this
from pathway_tpu.stdlib.temporal._window import Window


class WindowJoinResult:
    def __init__(self, left_assigned, right_assigned, on, mode, left_orig, right_orig):
        conds = list(on)
        conds.append(
            expr_mod.ColumnBinaryOpExpression(
                "==",
                ColumnReference(left_ph, "_pw_window"),
                ColumnReference(right_ph, "_pw_window"),
            )
        )
        self._jr = JoinResult(left_assigned, right_assigned, conds, mode=mode)
        self._left_orig = left_orig
        self._right_orig = right_orig
        self._left_assigned = left_assigned
        self._right_assigned = right_assigned

    def select(self, *args, **kwargs) -> Table:
        exprs: dict[str, Any] = {}
        for a in args:
            if isinstance(a, ColumnReference):
                exprs[a.name] = a
            else:
                raise ValueError("positional select args must be column refs")
        exprs.update(kwargs)
        mapping = {
            id(self._left_orig): self._left_assigned,
            id(self._right_orig): self._right_assigned,
        }
        final = {n: expr_mod._wrap(e)._substitute(mapping) for n, e in exprs.items()}
        return self._jr.select(**final)


def _assign(table: Table, time_expr, window: Window) -> Table:
    def windows_of(t):
        if t is None:
            return ()
        return tuple((s, e) for (s, e) in window._assign(t))

    w = table.with_columns(
        _pw_windows=ApplyExpression(windows_of, None, time_expr),
    )
    flat = w.flatten(ColumnReference(this, "_pw_windows"))
    return flat.with_columns(_pw_window=ColumnReference(this, "_pw_windows")).without(
        "_pw_windows"
    )


def window_join(
    self: Table,
    other: Table,
    self_time,
    other_time,
    window: Window,
    *on,
    how: JoinMode = JoinMode.INNER,
) -> WindowJoinResult:
    left_assigned = _assign(self, self_time, window)
    right_assigned = _assign(other, other_time, window)
    conds = []
    for cond in on:
        conds.append(
            expr_mod.ColumnBinaryOpExpression(
                "==",
                cond._left._substitute({id(self): left_assigned, id(this): left_assigned}),
                cond._right._substitute({id(other): right_assigned, id(this): right_assigned}),
            )
        )
    # substitute original table refs onto assigned tables
    fixed = []
    for cond in on:
        l_e = _sub_table(cond._left, self, left_assigned, other, right_assigned)
        r_e = _sub_table(cond._right, self, left_assigned, other, right_assigned)
        fixed.append(expr_mod.ColumnBinaryOpExpression("==", l_e, r_e))
    return WindowJoinResult(left_assigned, right_assigned, fixed, how, self, other)


def _sub_table(e, l_orig, l_new, r_orig, r_new):
    return e._substitute({id(l_orig): l_new, id(r_orig): r_new})


def window_join_inner(self, other, self_time, other_time, window, *on, **kw):
    kw.pop("how", None)
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.INNER, **kw)


def window_join_left(self, other, self_time, other_time, window, *on, **kw):
    kw.pop("how", None)
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.LEFT, **kw)


def window_join_right(self, other, self_time, other_time, window, *on, **kw):
    kw.pop("how", None)
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.RIGHT, **kw)


def window_join_outer(self, other, self_time, other_time, window, *on, **kw):
    kw.pop("how", None)
    return window_join(self, other, self_time, other_time, window, *on, how=JoinMode.OUTER, **kw)
