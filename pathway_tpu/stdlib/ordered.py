"""Ordered-table helpers (parity: stdlib/ordered/diff).

``pw.Table.diff`` — difference between a row and the previous row in the
order given by ``timestamp``, computed via the engine's sort (prev/next)
operator.
"""

from __future__ import annotations

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


def diff(table: Table, timestamp, *values, instance=None) -> Table:
    r"""Per-row difference vs the previous row in ``timestamp`` order
    (parity: stdlib/ordered/diff).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('t | v\n1 | 10\n2 | 13\n4 | 19')
    >>> r = pw.ordered.diff(t, pw.this.t, pw.this.v)
    >>> pw.debug.compute_and_print(r.select(pw.this.t, pw.this.diff_v), include_id=False)
    t | diff_v
    1 | None
    2 | 3
    4 | 6
    """
    sorted_t = table.sort(key=timestamp, instance=instance)
    exprs = {}
    for v in values:
        name = v.name if isinstance(v, ColumnReference) else str(v)
        prev_view = table.ix(sorted_t.prev, optional=True)
        exprs["diff_" + name] = expr_mod.if_else(
            getattr(prev_view, name).is_none() if hasattr(prev_view, name) else expr_mod.ColumnConstExpression(True),
            expr_mod.ColumnConstExpression(None),
            getattr(this, name) - getattr(prev_view, name),
        )
    out = table.with_columns(**exprs)
    return out


__all__ = ["diff"]
