"""stdlib: temporal, indexing, ml, graphs, stateful, statistical, ordered, utils."""

from pathway_tpu.stdlib import (
    graphs,
    indexing,
    ml,
    ordered,
    stateful,
    statistical,
    temporal,
    utils,
)

__all__ = [
    "graphs",
    "indexing",
    "ml",
    "ordered",
    "stateful",
    "statistical",
    "temporal",
    "utils",
]
