"""Stateful helpers (parity: stdlib/stateful: deduplicate)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table


def deduplicate(
    table: Table,
    *,
    value,
    instance=None,
    acceptor: Callable[[Any, Any], bool],
    persistent_id: str | None = None,
    name: str | None = None,
) -> Table:
    """Keep one row per instance; replace when acceptor(new, old) is True."""
    return table.deduplicate(
        value=value, instance=instance, acceptor=acceptor, persistent_id=persistent_id, name=name
    )


__all__ = ["deduplicate"]
