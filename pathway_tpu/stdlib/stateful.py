"""Stateful helpers (parity: stdlib/stateful: deduplicate)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table


def deduplicate(
    table: Table,
    *,
    value,
    instance=None,
    acceptor: Callable[[Any, Any], bool],
    persistent_id: str | None = None,
    name: str | None = None,
) -> Table:
    r"""Keep one row per instance; replace when acceptor(new, old) is True.

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.stdlib.stateful import deduplicate
    >>> t = pw.debug.table_from_markdown('k | v | _time\na | 1 | 2\na | 9 | 4')
    >>> r = deduplicate(t, value=pw.this.v, instance=pw.this.k, acceptor=lambda new, old: new > old)
    >>> pw.debug.compute_and_print(r.select(pw.this.v), include_id=False)
    v
    9
    """
    return table.deduplicate(
        value=value, instance=instance, acceptor=acceptor, persistent_id=persistent_id, name=name
    )


__all__ = ["deduplicate"]
