"""PageRank (parity: stdlib/graphs/pagerank.py) via pw.iterate."""

from __future__ import annotations

from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


def pagerank(edges: Table, steps: int = 5, damping: int = 85) -> Table:
    """Integer-arithmetic pagerank over an edge table (columns u, v).

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.stdlib.graphs.pagerank import pagerank
    >>> edges = pw.debug.table_from_markdown('''
    ... u | v
    ... a | b
    ... a | c
    ... b | c
    ... c | a
    ... ''')
    >>> g = edges.select(u=edges.pointer_from(pw.this.u), v=edges.pointer_from(pw.this.v))
    >>> ranks = pagerank(g, steps=3)
    >>> pw.debug.compute_and_print(ranks.select(pw.this.rank), include_id=False)
    rank
    104
    120
    71
    """
    # out-degrees
    degrees = edges.groupby(this.u).reduce(u=this.u, degree=reducers.count())
    vertices = (
        edges.select(v=this.u)
        .concat_reindex(edges.select(v=this.v))
        .groupby(this.v)
        .reduce(v=this.v)
    )

    def one_step(ranks: Table) -> dict:
        # flow along edges: each u sends rank/degree to each v
        from pathway_tpu.internals.thisclass import left as lp, right as rp
        import pathway_tpu.internals.expression as expr_mod

        with_deg = edges.join(
            degrees, ColumnReference(lp, "u") == ColumnReference(rp, "u")
        ).select(
            u=ColumnReference(lp, "u"),
            v=ColumnReference(lp, "v"),
            degree=ColumnReference(rp, "degree"),
        )
        with_rank = with_deg.join(
            ranks, ColumnReference(lp, "u") == ColumnReference(rp, "v")
        ).select(
            v=ColumnReference(lp, "v"),
            flow=ColumnReference(rp, "rank") // ColumnReference(lp, "degree"),
        )
        inflow = with_rank.groupby(this.v).reduce(
            v=this.v, total=reducers.sum(this.flow)
        )
        new_ranks = vertices.join_left(
            inflow, ColumnReference(lp, "v") == ColumnReference(rp, "v")
        ).select(
            v=ColumnReference(lp, "v"),
            rank=(100 - damping)
            + (damping * expr_mod.coalesce(ColumnReference(rp, "total"), 0)) // 100,
        )
        return dict(ranks=new_ranks)

    initial = vertices.select(v=this.v, rank=100)
    result = iterate(
        lambda ranks: one_step(ranks), iteration_limit=steps, ranks=initial
    )
    return result


__all__ = ["pagerank"]
