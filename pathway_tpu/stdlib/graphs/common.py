"""Graph schemas (parity: stdlib/graphs/common.py)."""

from __future__ import annotations

import dataclasses

from pathway_tpu.engine.types import Pointer
from pathway_tpu.internals.schema import Schema


class Vertex(Schema):
    pass


class Edge(Schema):
    u: Pointer
    v: Pointer


class Weight(Schema):
    weight: float


@dataclasses.dataclass
class Graph:
    V: object  # Table of vertices
    E: object  # Table of edges
