"""Graph algorithms over streaming tables (parity: stdlib/graphs/).

pagerank, bellman_ford, louvain — all built on ``pw.iterate`` fixed points,
as in the reference.
"""

from pathway_tpu.stdlib.graphs.common import Edge, Vertex, Graph
from pathway_tpu.stdlib.graphs.pagerank import pagerank
from pathway_tpu.stdlib.graphs.bellman_ford import bellman_ford
from pathway_tpu.stdlib.graphs.louvain_communities import louvain_level

__all__ = [
    "Edge",
    "Vertex",
    "Graph",
    "pagerank",
    "bellman_ford",
    "louvain_level",
]
