"""One level of Louvain community detection (parity: stdlib/graphs/louvain_communities.py).

Simplified greedy modularity pass: each vertex adopts the community that the
plurality of its neighbours hold, iterated to stability.
"""

from __future__ import annotations

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import left as lp, right as rp, this


def louvain_level(edges: Table, iteration_limit: int = 10) -> Table:
    """edges: (u, v) undirected; returns (v, community)."""
    vertices = (
        edges.select(v=this.u)
        .concat_reindex(edges.select(v=this.v))
        .groupby(this.v)
        .reduce(v=this.v)
    )
    both_dirs = edges.select(u=this.u, v=this.v).concat_reindex(
        edges.select(u=this.v, v=this.u)
    )
    initial = vertices.select(v=this.v, community=this.v)

    def step(assign: Table) -> dict:
        # join on column values, not row ids — v labels are arbitrary values
        # (strings/ints), so rekeying the assignment via with_id would break
        neigh = both_dirs.join(
            assign, ColumnReference(lp, "v") == ColumnReference(rp, "v")
        ).select(u=ColumnReference(lp, "u"), community=ColumnReference(rp, "community"))
        votes = neigh.groupby(this.u, this.community).reduce(
            u=this.u, community=this.community, n=reducers.count()
        )
        # deterministic preference: plurality, then the vertex's current
        # community (stops synchronous-update oscillation), then min label
        flagged = votes.join(
            assign, ColumnReference(lp, "u") == ColumnReference(rp, "v")
        ).select(
            u=ColumnReference(lp, "u"),
            community=ColumnReference(lp, "community"),
            score=expr_mod.make_tuple(
                ColumnReference(lp, "n"),
                expr_mod.if_else(
                    expr_mod.ColumnBinaryOpExpression(
                        "==",
                        ColumnReference(lp, "community"),
                        ColumnReference(rp, "community"),
                    ),
                    1,
                    0,
                ),
            ),
        )
        top = flagged.groupby(this.u).reduce(
            u=this.u, s=reducers.max(this.score)
        )
        tied = flagged.join(
            top, ColumnReference(lp, "u") == ColumnReference(rp, "u")
        ).select(
            u=ColumnReference(lp, "u"),
            community=ColumnReference(lp, "community"),
            ok=expr_mod.ColumnBinaryOpExpression(
                "==", ColumnReference(lp, "score"), ColumnReference(rp, "s")
            ),
        )
        chosen = (
            tied.filter(ColumnReference(this, "ok"))
            .groupby(this.u)
            .reduce(u=this.u, community=reducers.min(this.community))
        )
        # id=left.id keeps assignment rows keyed stably across rounds
        new_assign = assign.join_left(
            chosen,
            ColumnReference(lp, "v") == ColumnReference(rp, "u"),
            id=ColumnReference(lp, "id"),
        ).select(
            v=ColumnReference(lp, "v"),
            community=expr_mod.coalesce(
                ColumnReference(rp, "community"), ColumnReference(lp, "community")
            ),
        )
        return dict(assign=new_assign)

    return iterate(lambda assign: step(assign), iteration_limit=iteration_limit, assign=initial)


__all__ = ["louvain_level"]
