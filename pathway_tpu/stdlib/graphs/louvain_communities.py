"""One level of Louvain community detection (parity: stdlib/graphs/louvain_communities.py).

Simplified greedy modularity pass: each vertex adopts the community that the
plurality of its neighbours hold, iterated to stability.
"""

from __future__ import annotations

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import left as lp, right as rp, this


def louvain_level(edges: Table, iteration_limit: int = 10) -> Table:
    """edges: (u, v) undirected; returns (v, community)."""
    vertices = (
        edges.select(v=this.u)
        .concat_reindex(edges.select(v=this.v))
        .groupby(this.v)
        .reduce(v=this.v)
    )
    both_dirs = edges.select(u=this.u, v=this.v).concat_reindex(
        edges.select(u=this.v, v=this.u)
    )
    initial = vertices.select(v=this.v, community=this.v)

    def step(assign: Table) -> dict:
        keyed = assign.with_id(ColumnReference(this, "v"))
        neigh = both_dirs.join(
            keyed, ColumnReference(lp, "v") == ColumnReference(rp, "v")
        ).select(u=ColumnReference(lp, "u"), community=ColumnReference(rp, "community"))
        votes = neigh.groupby(this.u, this.community).reduce(
            u=this.u, community=this.community, n=reducers.count()
        )
        best = votes.groupby(this.u).reduce(
            u=this.u,
            best=reducers.argmax(this.n),
        )
        chosen = best.select(
            u=this.u,
            community=votes.ix(this.best).community,
        )
        keyed_chosen = chosen.with_id(ColumnReference(this, "u"))
        new_assign = assign.join_left(
            keyed_chosen,
            ColumnReference(lp, "v") == ColumnReference(rp, "id"),
        ).select(
            v=ColumnReference(lp, "v"),
            community=expr_mod.coalesce(
                ColumnReference(rp, "community"), ColumnReference(lp, "community")
            ),
        )
        return dict(assign=new_assign)

    return iterate(lambda assign: step(assign), iteration_limit=iteration_limit, assign=initial)


__all__ = ["louvain_level"]
