"""Bellman–Ford shortest paths (parity: stdlib/graphs/bellman_ford.py)."""

from __future__ import annotations

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.iterate import iterate
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import left as lp, right as rp, this


def bellman_ford(vertices: Table, edges: Table, iteration_limit: int | None = None) -> Table:
    r"""Single-source shortest paths (parity: stdlib/graphs/bellman_ford).

    ``vertices``: columns (is_source: bool); ``edges``: (u, v, dist) with
    u/v pointing at vertex ids.  Returns dist_from_source per vertex id.

    Example:

    >>> import pathway_tpu as pw
    >>> vertices = pw.debug.table_from_markdown('''
    ...   | is_source
    ... A | True
    ... B | False
    ... C | False
    ... ''')
    >>> edges = pw.debug.table_from_markdown('''
    ... lu | lv | dist
    ... A  | B  | 1.0
    ... B  | C  | 2.0
    ... A  | C  | 10.0
    ... ''').select(
    ...     u=vertices.pointer_from(pw.this.lu),
    ...     v=vertices.pointer_from(pw.this.lv),
    ...     dist=pw.this.dist,
    ... )
    >>> res = pw.graphs.bellman_ford(vertices, edges, iteration_limit=5)
    >>> pw.debug.compute_and_print(res, include_id=False)
    dist
    0.0
    1.0
    3.0
    """
    initial = vertices.select(
        dist=expr_mod.if_else(this.is_source, 0.0, float("inf"))
    )

    def step(state: Table) -> dict:
        relaxed = edges.join(
            state, ColumnReference(lp, "u") == ColumnReference(rp, "id")
        ).select(
            v=ColumnReference(lp, "v"),
            cand=ColumnReference(rp, "dist") + ColumnReference(lp, "dist"),
        )
        best = relaxed.groupby(this.v).reduce(
            v=this.v, cand=reducers.min(this.cand)
        )
        keyed_best = best.with_id(ColumnReference(this, "v"))
        # id=left.id keeps the state keyed by vertex id across rounds — the
        # next round's edges⋈state lookup depends on it
        new_state = state.join_left(
            keyed_best,
            ColumnReference(lp, "id") == ColumnReference(rp, "id"),
            id=ColumnReference(lp, "id"),
        ).select(
            dist=expr_mod.apply_with_type(
                lambda d, c: d if c is None else min(d, c),
                float,
                ColumnReference(lp, "dist"),
                ColumnReference(rp, "cand"),
            ),
        )
        return dict(state=new_state)

    result = iterate(lambda state: step(state), iteration_limit=iteration_limit, state=initial)
    return result


__all__ = ["bellman_ford"]
