"""HNSW approximate nearest-neighbor index.

Parity target: the reference's USearch integration
(``/root/reference/src/external_integration/usearch_integration.rs:163``),
which links the USearch C library.  This build implements the HNSW
algorithm (Malkov & Yashunin 2016) directly, twice:

* ``NativeHnswIndex`` — the production path: graph, vector store and the
  insert/search hot loops live in the C++ native core
  (``native/src/_native.cpp`` ``hnsw_*``), the same division of labor as
  the reference linking the USearch C library.  The Python side keeps
  128-bit-key↔dense-id mapping, metadata filters, and the
  tombstone-compaction policy.
* ``PyHnswIndex`` — the dependency-free fallback (numpy-vectorized per
  candidate frontier), used when the native core is unavailable
  (``PATHWAY_NATIVE=0`` or no compiler).

Both honor the same tuning knobs — ``connectivity`` (M),
``expansion_add`` (efConstruction), ``expansion_search`` (ef) — and the
same scoring conventions.  ``HnswIndex(...)`` picks the best available.
Deletions are tombstoned and compacted when they exceed half the index
(USearch marks-and-skips the same way).
"""

from __future__ import annotations

import heapq
import math
import random
from typing import Any, Callable

import numpy as np


def HnswIndex(
    metric: str = "cos",
    connectivity: int = 16,
    expansion_add: int = 128,
    expansion_search: int = 64,
    seed: int = 0,
):
    """The best available HNSW implementation (native core, else Python)."""
    from pathway_tpu import native as native_mod

    nat = native_mod.get()
    if nat is not None and hasattr(nat, "hnsw_new"):
        return NativeHnswIndex(
            metric=metric,
            connectivity=connectivity,
            expansion_add=expansion_add,
            expansion_search=expansion_search,
            seed=seed,
        )
    return PyHnswIndex(
        metric=metric,
        connectivity=connectivity,
        expansion_add=expansion_add,
        expansion_search=expansion_search,
        seed=seed,
    )


class NativeHnswIndex:
    """C++-cored HNSW with the engine's external-index duck type.

    Keys are the engine's 128-bit row keys (arbitrary Python ints); the
    native graph works on dense u32 node ids.  In-place updates tombstone
    the old node and insert a fresh one; when tombstones outnumber live
    nodes the index is rebuilt from the retained raw vectors (USearch's
    compaction analog).
    """

    def __init__(
        self,
        metric: str = "cos",
        connectivity: int = 16,
        expansion_add: int = 128,
        expansion_search: int = 64,
        seed: int = 0,
    ):
        if metric not in ("cos", "l2sq", "ip"):
            raise ValueError(f"unknown metric {metric!r}")
        from pathway_tpu import native as native_mod

        self._nat = native_mod.get()
        self.metric = metric
        self.m = max(2, int(connectivity) or 16)
        self.ef_construction = max(self.m, int(expansion_add) or 128)
        self.ef_search = max(1, int(expansion_search) or 64)
        self._seed = seed
        self._dim: int | None = None
        self._h = None
        self._node_of_key: dict[int, int] = {}
        self._key_of_node: dict[int, int] = {}
        self._filters: dict[int, Any] = {}
        self._n_dead = 0

    def __len__(self) -> int:
        return len(self._node_of_key)

    def _ensure(self, dim: int):
        if self._h is None:
            self._dim = dim
            self._h = self._nat.hnsw_new(
                dim, self.metric, self.m, self.ef_construction, self._seed
            )
        elif dim != self._dim:
            raise ValueError(f"dimension mismatch: {dim} != {self._dim}")
        return self._h

    def add(self, key: int, vector, filter_data=None) -> None:
        v = np.ascontiguousarray(np.asarray(vector, np.float32).reshape(-1))
        h = self._ensure(v.shape[0])
        old = self._node_of_key.pop(key, None)
        if old is not None:
            # in-place update: tombstone + fresh insert
            self._nat.hnsw_remove(h, old)
            self._key_of_node.pop(old, None)
            self._n_dead += 1
        node = self._nat.hnsw_add(h, v)
        self._node_of_key[key] = node
        self._key_of_node[node] = key
        if filter_data is not None:
            self._filters[key] = filter_data
        else:
            self._filters.pop(key, None)
        self._maybe_compact()

    def remove(self, key: int) -> None:
        node = self._node_of_key.pop(key, None)
        if node is None:
            return
        self._nat.hnsw_remove(self._h, node)
        self._key_of_node.pop(node, None)
        self._filters.pop(key, None)
        self._n_dead += 1
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        """Rebuild once tombstones outnumber live nodes — update-only churn
        counts too, not just removals (every in-place add tombstones)."""
        if self._n_dead > len(self._node_of_key):
            self._compact()

    def _compact(self) -> None:
        # live vectors are read back from the native store (prepped form —
        # re-prepping is idempotent), so Python never mirrors the vectors
        nat, h = self._nat, self._h
        live = [
            (
                k,
                np.frombuffer(nat.hnsw_get_vector(h, node), np.float32),
                self._filters.get(k),
            )
            for k, node in self._node_of_key.items()
        ]
        self._h = None
        self._node_of_key.clear()
        self._key_of_node.clear()
        self._filters.clear()
        self._n_dead = 0
        for k, v, f in live:
            self.add(k, v, f)

    def search(
        self,
        query,
        k: int | None,
        filter_query=None,
        ef: int | None = None,
    ) -> list[tuple[int, float]]:
        from pathway_tpu.stdlib.indexing.filters import metadata_matches

        if k is None:
            k = 3
        if self._h is None or not self._node_of_key:
            return []
        q = np.ascontiguousarray(np.asarray(query, np.float32).reshape(-1))
        if q.shape[0] != self._dim:
            raise ValueError(f"dimension mismatch: {q.shape[0]} != {self._dim}")
        ef = max(ef or self.ef_search, k)
        pairs = self._nat.hnsw_search(self._h, q, k, ef)
        out: list[tuple[int, float]] = []
        for node, dist in pairs:
            key = self._key_of_node.get(node)
            if key is None:
                continue
            if filter_query is not None and not metadata_matches(
                filter_query, self._filters.get(key)
            ):
                continue
            # same conventions as the brute-force index: similarity for
            # cos/ip (dist = -similarity), distance for l2sq
            score = float(dist) if self.metric == "l2sq" else -float(dist)
            out.append((key, score))
            if len(out) >= k:
                break
        return out


class PyHnswIndex:
    """add/remove/search with the engine's external-index duck type."""

    def __init__(
        self,
        metric: str = "cos",
        connectivity: int = 16,
        expansion_add: int = 128,
        expansion_search: int = 64,
        seed: int = 0,
    ):
        if metric not in ("cos", "l2sq", "ip"):
            raise ValueError(f"unknown metric {metric!r}")
        self.metric = metric
        self.m = max(2, int(connectivity) or 16)
        self.m0 = 2 * self.m
        self.ef_construction = max(self.m, int(expansion_add) or 128)
        self.ef_search = max(1, int(expansion_search) or 64)
        self._ml = 1.0 / math.log(self.m)
        self._rng = random.Random(seed)

        self._vectors: dict[int, np.ndarray] = {}  # raw (unnormalized)
        self._prepped: dict[int, np.ndarray] = {}  # metric-prepped
        self._filters: dict[int, Any] = {}
        self._levels: dict[int, int] = {}
        # per-layer adjacency: layer -> key -> [neighbor keys]
        self._links: list[dict[int, list[int]]] = []
        # reverse edges: target -> {(layer, source)} — makes in-place
        # updates O(degree) instead of a full-graph scan
        self._rev: dict[int, set[tuple[int, int]]] = {}
        self._entry: int | None = None
        self._deleted: set[int] = set()

    # -- metric helpers ----------------------------------------------------

    def _prep(self, v: np.ndarray) -> np.ndarray:
        v = np.asarray(v, dtype=np.float32).reshape(-1)
        if self.metric == "cos":
            n = float(np.linalg.norm(v))
            return v / n if n > 0 else v
        return v

    def _dists(self, q: np.ndarray, keys: list[int]) -> np.ndarray:
        """Distances (lower = closer) from prepped q to prepped keys."""
        mat = np.stack([self._prepped[k] for k in keys])
        if self.metric == "l2sq":
            d = mat - q[None, :]
            return np.einsum("ij,ij->i", d, d)
        # cos / ip: similarity -> distance
        return -(mat @ q)

    def _score(self, dist: float) -> float:
        """Report scores with the brute-force index's conventions:
        similarity for cos/ip (higher better), distance for l2sq."""
        if self.metric == "l2sq":
            return float(dist)
        return -float(dist)  # dist = -similarity → score = similarity

    # -- construction ------------------------------------------------------

    def __len__(self) -> int:
        return len(self._vectors) - len(self._deleted)

    def _set_links(self, layer: int, src: int, new_list: list[int]) -> None:
        """Replace src's adjacency on a layer, keeping reverse edges in sync."""
        old = self._links[layer].get(src, ())
        for t in old:
            self._rev.get(t, set()).discard((layer, src))
        self._links[layer][src] = new_list
        for t in new_list:
            self._rev.setdefault(t, set()).add((layer, src))

    def add(self, key: int, vector, filter_data=None) -> None:
        if key in self._vectors:
            # in-place update / re-insert: fully unlink the old node so the
            # fresh insert can't find its own stale edges (self-links)
            self._unlink(key)
        self._deleted.discard(key)
        v = np.asarray(vector, dtype=np.float32).reshape(-1)
        self._vectors[key] = v
        self._prepped[key] = self._prep(v)
        if filter_data is not None:
            self._filters[key] = filter_data
        level = int(-math.log(max(self._rng.random(), 1e-12)) * self._ml)
        self._levels[key] = level
        while len(self._links) <= level:
            self._links.append({})
        for layer in range(level + 1):
            self._links[layer].setdefault(key, [])

        if self._entry is None or not self._live_entry():
            self._entry = key
            return

        q = self._prepped[key]
        ep = [self._entry]
        top = self._levels[self._entry]
        # greedy descent above the insertion level
        for layer in range(top, level, -1):
            ep = [self._greedy(q, ep[0], layer)]
        # beam search + linking from min(level, top) down to 0
        for layer in range(min(level, top), -1, -1):
            cands = self._search_layer(q, ep, layer, self.ef_construction)
            m_max = self.m0 if layer == 0 else self.m
            chosen = [k for (_d, k) in heapq.nsmallest(self.m, cands) if k != key]
            self._set_links(layer, key, list(chosen))
            for nb in chosen:
                lst = self._links[layer].get(nb, []) + [key]
                if len(lst) > m_max:
                    # prune: keep the m_max closest to nb
                    nbv = self._prepped[nb]
                    d = self._dists(nbv, lst)
                    order = np.argsort(d)[:m_max]
                    lst = [lst[i] for i in order]
                self._set_links(layer, nb, lst)
            ep = [k for (_d, k) in cands] or ep
        if level > self._levels.get(self._entry, 0):
            self._entry = key

    def remove(self, key: int) -> None:
        if key not in self._vectors or key in self._deleted:
            return
        self._deleted.add(key)
        self._filters.pop(key, None)
        if len(self._deleted) * 2 > len(self._vectors):
            self._compact()
        elif key == self._entry:
            self._entry = self._pick_entry()

    def _unlink(self, key: int) -> None:
        """Remove a node and every edge referencing it (for re-inserts).

        O(degree) via the reverse-edge index — a full-graph scan here would
        make streaming in-place updates quadratic."""
        for layer_idx, src in list(self._rev.get(key, ())):
            lst = self._links[layer_idx].get(src)
            if lst and key in lst:
                self._links[layer_idx][src] = [x for x in lst if x != key]
        self._rev.pop(key, None)
        for layer_idx, layer in enumerate(self._links):
            out = layer.pop(key, None)
            if out:
                for t in out:
                    self._rev.get(t, set()).discard((layer_idx, key))
        self._vectors.pop(key, None)
        self._prepped.pop(key, None)
        self._filters.pop(key, None)
        self._levels.pop(key, None)
        self._deleted.discard(key)
        if key == self._entry:
            self._entry = self._pick_entry()

    def _live_entry(self) -> bool:
        return self._entry is not None and self._entry not in self._deleted

    def _pick_entry(self) -> int | None:
        best, best_level = None, -1
        for k, lvl in self._levels.items():
            if k not in self._deleted and lvl > best_level:
                best, best_level = k, lvl
        return best

    def _compact(self) -> None:
        """Rebuild without tombstones (USearch's compaction analog)."""
        live = [
            (k, self._vectors[k], self._filters.get(k))
            for k in self._vectors
            if k not in self._deleted
        ]
        self._vectors.clear()
        self._prepped.clear()
        self._filters.clear()
        self._levels.clear()
        self._links = []
        self._rev = {}
        self._entry = None
        self._deleted.clear()
        for k, v, f in live:
            self.add(k, v, f)

    # -- search ------------------------------------------------------------

    def _greedy(self, q: np.ndarray, start: int, layer: int) -> int:
        cur = start
        cur_d = float(self._dists(q, [cur])[0])
        improved = True
        while improved:
            improved = False
            nbs = [n for n in self._links[layer].get(cur, []) if n in self._prepped]
            if not nbs:
                break
            d = self._dists(q, nbs)
            i = int(np.argmin(d))
            if float(d[i]) < cur_d:
                cur, cur_d = nbs[i], float(d[i])
                improved = True
        return cur

    def _search_layer(
        self, q: np.ndarray, entry_points: list[int], layer: int, ef: int
    ) -> list[tuple[float, int]]:
        """Beam search; returns [(dist, key)] of up to ef nearest (live or
        tombstoned — callers filter)."""
        visited = set(entry_points)
        d0 = self._dists(q, entry_points)
        cand: list[tuple[float, int]] = [
            (float(d), k) for d, k in zip(d0, entry_points)
        ]
        heapq.heapify(cand)
        best: list[tuple[float, int]] = [(-c[0], c[1]) for c in cand]
        heapq.heapify(best)  # max-heap via negation
        while cand:
            d, k = heapq.heappop(cand)
            if best and d > -best[0][0] and len(best) >= ef:
                break
            nbs = [
                n
                for n in dict.fromkeys(self._links[layer].get(k, ()))
                if n not in visited and n in self._prepped
            ]
            if not nbs:
                continue
            visited.update(nbs)
            dists = self._dists(q, nbs)
            worst = -best[0][0] if best else float("inf")
            for dist, n in zip(dists, nbs):
                dist = float(dist)
                if len(best) < ef or dist < worst:
                    heapq.heappush(cand, (dist, n))
                    heapq.heappush(best, (-dist, n))
                    if len(best) > ef:
                        heapq.heappop(best)
                    worst = -best[0][0]
        return sorted((-nd, k) for (nd, k) in best)

    def search(
        self,
        query,
        k: int | None,
        filter_query=None,
        ef: int | None = None,
    ) -> list[tuple[int, float]]:
        from pathway_tpu.stdlib.indexing.filters import metadata_matches

        if k is None:
            k = 3
        if not self._live_entry():
            self._entry = self._pick_entry()
        if self._entry is None:
            return []
        q = self._prep(np.asarray(query, dtype=np.float32).reshape(-1))
        ef = max(ef or self.ef_search, k)
        ep = self._entry
        for layer in range(self._levels[self._entry], 0, -1):
            ep = self._greedy(q, ep, layer)
        found = self._search_layer(q, [ep], 0, ef)
        out: list[tuple[int, float]] = []
        for dist, key in found:
            if key in self._deleted:
                continue
            if filter_query is not None and not metadata_matches(
                filter_query, self._filters.get(key)
            ):
                continue
            out.append((key, self._score(dist)))
            if len(out) >= k:
                break
        return out
