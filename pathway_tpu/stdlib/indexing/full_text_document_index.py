"""Default full-text (BM25) document index.

Parity target: ``python/pathway/stdlib/indexing/full_text_document_index.py``.
"""

from __future__ import annotations

from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25
from pathway_tpu.stdlib.indexing.data_index import DataIndex


def default_full_text_document_index(
    data_column,
    data_table,
    *,
    metadata_column=None,
) -> DataIndex:
    """A DataIndex over an arbitrary full-text (BM25) inner index — a
    development/demo default, like the vector variants."""
    inner = TantivyBM25(data_column, metadata_column=metadata_column)
    return DataIndex(data_table, inner)
