"""DataIndex — joins index answers back to data (parity:
stdlib/indexing/data_index.py:278-412).

``query_as_of_now`` lowers onto the engine's as-of-now external-index
operator (§3.4 of SURVEY.md): queries are a stream; each is answered against
current index state, and answers are kept up to date under data changes with
retraction bookkeeping.  The answer join-back (data_index.py:294-349) is
composed from flatten → ix → groupby, all incremental.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ApplyExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


class InnerIndex:
    """Factory-facing half of an index (parity: data_index.py:206)."""

    def __init__(self, data_column: ColumnReference, metadata_column: ColumnReference | None = None):
        self.data_column = data_column
        self.metadata_column = metadata_column

    def factory(self):
        """Return an engine index factory (object with .build())."""
        raise NotImplementedError

    def embed(self, column):
        """Optionally turn a raw query column into the index's vector space."""
        return column

    def data_expr(self, index_column):
        """Expression producing what the engine index stores per data row
        (embeds the data column when an embedder is attached)."""
        embedder = getattr(self, "embedder", None)
        if embedder is not None:
            return embedder(index_column)
        return index_column


class DataIndex:
    """Index over ``data_table`` with query methods returning result tables."""

    def __init__(self, data_table: Table, inner_index: InnerIndex):
        self.data_table = data_table
        self.inner_index = inner_index

    def query_as_of_now(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: int | Any = 3,
        collapse_rows: bool = True,
        metadata_filter: ColumnReference | None = None,
        with_distances: bool = True,
    ) -> Table:
        return self._query(
            query_column,
            number_of_matches=number_of_matches,
            collapse_rows=collapse_rows,
            metadata_filter=metadata_filter,
        )

    # plain query shares the lowering; the external-index operator already
    # revises answers on data change, which is the full incremental semantics
    def query(self, query_column: ColumnReference, **kwargs) -> Table:
        kwargs.pop("collapse_rows", None)
        return self._query(query_column, **kwargs)

    def _query(
        self,
        query_column: ColumnReference,
        *,
        number_of_matches: int | Any = 3,
        collapse_rows: bool = True,
        metadata_filter: ColumnReference | None = None,
    ) -> Table:
        query_table: Table = query_column.table
        data_table = self.data_table
        index_col = self.inner_index.data_column
        embedded_q = self.inner_index.embed(query_column)
        if embedded_q is not query_column:
            query_table = query_table.with_columns(_pw_q_embedded=embedded_q)
            q_col = ColumnReference(query_table, "_pw_q_embedded")
        else:
            q_col = query_column
        data_expr = self.inner_index.data_expr(index_col)
        if data_expr is not index_col:
            # embed the data column device-side before it enters the index
            data_table = data_table.with_columns(_pw_data_prepared=data_expr)
            index_col = ColumnReference(data_table, "_pw_data_prepared")
        replies = data_table._external_index_as_of_now(
            self.inner_index.factory(),
            query_table,
            index_col,
            q_col,
            index_filter_data_column=self.inner_index.metadata_column,
            query_filter_column=metadata_filter,
            query_number_of_matches=number_of_matches,
        )
        # replies: universe of query_table; _pw_index_reply = sorted tuple of
        # (Pointer, score)
        data_names = [
            n for n in data_table.column_names() if not n.startswith("_pw_")
        ]

        ranked = replies.with_columns(
            _pw_ranked=ApplyExpression(
                lambda reply: tuple((p, s, i) for i, (p, s) in enumerate(reply)),
                None,
                ColumnReference(this, "_pw_index_reply"),
            )
        )
        flat = ranked.flatten(ColumnReference(this, "_pw_ranked"), origin_id="_pw_qid")
        flat = flat.with_columns(
            _pw_match=ApplyExpression(lambda r: r[0], None, ColumnReference(this, "_pw_ranked")),
            _pw_score=ApplyExpression(lambda r: r[1], None, ColumnReference(this, "_pw_ranked")),
            _pw_rank=ApplyExpression(lambda r: r[2], None, ColumnReference(this, "_pw_ranked")),
        )
        view = data_table.ix(ColumnReference(this, "_pw_match"))
        enriched_exprs: dict[str, Any] = {
            "_pw_qid": ColumnReference(this, "_pw_qid"),
            "_pw_score": ColumnReference(this, "_pw_score"),
            "_pw_rank": ColumnReference(this, "_pw_rank"),
        }
        for n in data_names:
            enriched_exprs[n] = getattr(view, n)
        enriched = flat.select(**enriched_exprs)

        if not collapse_rows:
            out_exprs: dict[str, Any] = {n: ColumnReference(this, n) for n in data_names}
            out_exprs["_pw_index_reply_score"] = ColumnReference(this, "_pw_score")
            out_exprs["_pw_query_id"] = ColumnReference(this, "_pw_qid")
            return enriched.select(**out_exprs)

        agg: dict[str, Any] = {"_pw_qid": ColumnReference(this, "_pw_qid")}
        for n in data_names:
            agg[n] = reducers.tuple(
                ColumnReference(this, n), sort_by=ColumnReference(this, "_pw_rank")
            )
        agg["_pw_index_reply_score"] = reducers.tuple(
            ColumnReference(this, "_pw_score"), sort_by=ColumnReference(this, "_pw_rank")
        )
        grouped = enriched.groupby(ColumnReference(this, "_pw_qid")).reduce(**agg)
        collected = grouped.with_id(ColumnReference(this, "_pw_qid"))
        cview = collected.ix(ColumnReference(this, "id"), optional=True)

        final: dict[str, Any] = {}
        for n in query_table.column_names():
            if n.startswith("_pw_"):
                continue
            final[n] = ColumnReference(this, n)
        for n in data_names:
            final[n] = expr_mod.coalesce(cview[n], ())
        final["_pw_index_reply_score"] = expr_mod.coalesce(
            cview["_pw_index_reply_score"], ()
        )
        return query_table.select(**final)
