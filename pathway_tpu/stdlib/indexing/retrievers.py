"""Retriever factory enums/abstracts (parity: stdlib/indexing/retrievers.py)."""

from __future__ import annotations

import dataclasses
import enum


class USearchMetricKind(enum.Enum):
    # mirrors usearch MetricKind (usearch_integration.rs)
    COS = "cos"
    L2SQ = "l2sq"
    IP = "ip"


class BruteForceKnnMetricKind(enum.Enum):
    # mirrors brute_force_knn_integration.rs metric kinds
    COS = "cos"
    L2SQ = "l2sq"


class AbstractRetrieverFactory:
    def build_index(self, data_column, data_table, metadata_column=None):
        raise NotImplementedError


@dataclasses.dataclass
class BruteForceKnnFactory(AbstractRetrieverFactory):
    """Factory for the dense device-backed index (parity: retrievers.py)."""

    dimensions: int | None = None
    reserved_space: int = 0
    embedder: object | None = None
    metric: "BruteForceKnnMetricKind" = None  # type: ignore[assignment]
    mesh: object | None = None  # jax.sharding.Mesh → corpus-sharded device index

    def build_index(self, data_column, data_table, metadata_column=None):
        from pathway_tpu.stdlib.indexing.data_index import DataIndex
        from pathway_tpu.stdlib.indexing.nearest_neighbors import (
            BruteForceKnn,
            DistanceMetric,
        )

        metric = self.metric or BruteForceKnnMetricKind.COS
        inner = BruteForceKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=DistanceMetric(metric.value),
            embedder=self.embedder,
            mesh=self.mesh,
        )
        return DataIndex(data_table, inner)


@dataclasses.dataclass
class UsearchKnnFactory(AbstractRetrieverFactory):
    """Factory keeping USearch HNSW API parity (shares the dense backend)."""

    dimensions: int | None = None
    reserved_space: int = 0
    embedder: object | None = None
    metric: "USearchMetricKind" = None  # type: ignore[assignment]
    connectivity: int = 0
    expansion_add: int = 0
    expansion_search: int = 0
    mesh: object | None = None  # jax.sharding.Mesh → corpus-sharded device index

    def build_index(self, data_column, data_table, metadata_column=None):
        from pathway_tpu.stdlib.indexing.data_index import DataIndex
        from pathway_tpu.stdlib.indexing.nearest_neighbors import (
            DistanceMetric,
            USearchKnn,
        )

        metric = self.metric or USearchMetricKind.COS
        inner = USearchKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            reserved_space=self.reserved_space,
            metric=DistanceMetric(metric.value),
            connectivity=self.connectivity,
            expansion_add=self.expansion_add,
            expansion_search=self.expansion_search,
            embedder=self.embedder,
            mesh=self.mesh,
        )
        return DataIndex(data_table, inner)


@dataclasses.dataclass
class TantivyBM25Factory(AbstractRetrieverFactory):
    """Factory for the BM25 full-text index."""

    ram_budget: int = 50_000_000
    in_memory_index: bool = True

    def build_index(self, data_column, data_table, metadata_column=None):
        from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25
        from pathway_tpu.stdlib.indexing.data_index import DataIndex

        inner = TantivyBM25(
            data_column,
            metadata_column,
            ram_budget=self.ram_budget,
            in_memory_index=self.in_memory_index,
        )
        return DataIndex(data_table, inner)


@dataclasses.dataclass
class HybridIndexFactory(AbstractRetrieverFactory):
    """Reciprocal-rank fusion over several retriever factories."""

    retriever_factories: list = None  # type: ignore[assignment]
    k: float = 60.0

    def build_index(self, data_column, data_table, metadata_column=None):
        from pathway_tpu.stdlib.indexing.data_index import DataIndex
        from pathway_tpu.stdlib.indexing.hybrid_index import HybridDataIndex

        indexes = [
            f.build_index(data_column, data_table, metadata_column)
            for f in self.retriever_factories
        ]
        return HybridDataIndex(data_table, indexes, k=self.k)



@dataclasses.dataclass
class LshKnnFactory(AbstractRetrieverFactory):
    """Factory for LSH-bucketed approximate KNN (parity:
    nearest_neighbors.py:528)."""

    dimensions: int | None = None
    n_or: int = 20
    n_and: int = 10
    bucket_length: float = 10.0
    distance_type: str = "euclidean"
    embedder: object | None = None

    def build_index(self, data_column, data_table, metadata_column=None):
        from pathway_tpu.stdlib.indexing.data_index import DataIndex
        from pathway_tpu.stdlib.indexing.nearest_neighbors import LshKnn

        if not isinstance(self.dimensions, int):
            # fail at configuration time, not mid-run inside rng.normal
            raise ValueError("LshKnnFactory requires dimensions= (int)")

        from pathway_tpu.stdlib.indexing.nearest_neighbors import DistanceMetric

        metric = (
            DistanceMetric.COS
            if self.distance_type == "cosine"
            else DistanceMetric.L2SQ
        )
        inner = LshKnn(
            data_column,
            metadata_column,
            dimensions=self.dimensions,
            n_or=self.n_or,
            n_and=self.n_and,
            bucket_length=self.bucket_length,
            metric=metric,
            embedder=self.embedder,
        )
        return DataIndex(data_table, inner)
