"""Retriever factory enums/abstracts (parity: stdlib/indexing/retrievers.py)."""

from __future__ import annotations

import enum


class USearchMetricKind(enum.Enum):
    # mirrors usearch MetricKind (usearch_integration.rs)
    COS = "cos"
    L2SQ = "l2sq"
    IP = "ip"


class BruteForceKnnMetricKind(enum.Enum):
    # mirrors brute_force_knn_integration.rs metric kinds
    COS = "cos"
    L2SQ = "l2sq"


class AbstractRetrieverFactory:
    def build_index(self, data_column, data_table, metadata_column=None):
        raise NotImplementedError
