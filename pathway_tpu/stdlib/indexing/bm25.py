"""BM25 full-text index (parity: stdlib/indexing/bm25.py:41 +
src/external_integration/tantivy_integration.rs).

Host-side inverted index with incremental add/remove and Okapi BM25 scoring —
the role tantivy plays in the reference.  Text scoring is not a TPU-shaped
workload (sparse, integer-heavy), so it stays on host by design; hybrid
fusion combines it with the device-side dense index.
"""

from __future__ import annotations

import math
import re
from collections import Counter, defaultdict
from typing import Any

from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.stdlib.indexing.data_index import InnerIndex
from pathway_tpu.stdlib.indexing.filters import metadata_matches

_WORD = re.compile(r"\w+")


def _tokenize(text: str) -> list[str]:
    return [w.lower() for w in _WORD.findall(text or "")]


class BM25Index:
    def __init__(self, k1: float = 1.2, b: float = 0.75):
        self.k1 = k1
        self.b = b
        self._docs: dict[int, Counter] = {}
        self._doc_len: dict[int, int] = {}
        self._filters: dict[int, Any] = {}
        self._postings: dict[str, set[int]] = defaultdict(set)
        self._total_len = 0

    def add(self, key: int, text, filter_data=None) -> None:
        tokens = Counter(_tokenize(text if isinstance(text, str) else str(text)))
        self._docs[key] = tokens
        n = sum(tokens.values())
        self._doc_len[key] = n
        self._total_len += n
        if filter_data is not None:
            self._filters[key] = filter_data
        for t in tokens:
            self._postings[t].add(key)

    def remove(self, key: int) -> None:
        tokens = self._docs.pop(key, None)
        if tokens is None:
            return
        self._total_len -= self._doc_len.pop(key, 0)
        self._filters.pop(key, None)
        for t in tokens:
            s = self._postings.get(t)
            if s:
                s.discard(key)
                if not s:
                    del self._postings[t]

    def search(self, query, k: int | None, filter_query=None) -> list[tuple[int, float]]:
        if k is None:
            k = 3
        q_tokens = _tokenize(query if isinstance(query, str) else str(query))
        n_docs = len(self._docs)
        if n_docs == 0 or not q_tokens:
            return []
        avgdl = self._total_len / n_docs
        scores: Counter = Counter()
        for t in q_tokens:
            postings = self._postings.get(t)
            if not postings:
                continue
            idf = math.log(1 + (n_docs - len(postings) + 0.5) / (len(postings) + 0.5))
            for key in postings:
                tf = self._docs[key][t]
                dl = self._doc_len[key]
                denom = tf + self.k1 * (1 - self.b + self.b * dl / avgdl)
                scores[key] += idf * tf * (self.k1 + 1) / denom
        out = []
        for key, score in scores.most_common():
            if filter_query is not None and not metadata_matches(
                filter_query, self._filters.get(key)
            ):
                continue
            out.append((key, float(score)))
            if len(out) >= k:
                break
        return out


class TantivyBM25(InnerIndex):
    """BM25 inner index (API parity with stdlib/indexing/bm25.py:41)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
        *,
        ram_budget: int = 50_000_000,
        in_memory_index: bool = True,
    ):
        super().__init__(data_column, metadata_column)

    def factory(self):
        class _F:
            @staticmethod
            def build():
                return BM25Index()

        return _F()

