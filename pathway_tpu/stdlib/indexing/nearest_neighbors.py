"""KNN inner indexes (parity: stdlib/indexing/nearest_neighbors.py:65-262
and src/external_integration/{brute_force_knn,usearch}_integration.rs).

``BruteForceKnn`` is the TPU-first index: vectors are packed into a matrix
and top-k is a (jit-compiled) matmul + top_k — see
``pathway_tpu/ops/topk.py``.  ``LshKnn`` is the pure-host LSH analog of the
reference's ``ml/classifiers/_knn_lsh.py``.  ``USearchKnn`` is approximate:
an HNSW graph (``hnsw.py``) honoring the USearch tuning parameters
(connectivity / expansion_add / expansion_search) — pick it over
``BruteForceKnn`` when corpus size makes the exact device scan too slow
and bounded recall loss is acceptable.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.filters import metadata_matches
from pathway_tpu.stdlib.indexing.retrievers import (
    AbstractRetrieverFactory,
    BruteForceKnnMetricKind,
    USearchMetricKind,
)


class DistanceMetric(enum.Enum):
    COS = "cos"
    L2SQ = "l2sq"
    IP = "ip"


def _as_vec(v) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v.astype(np.float32, copy=False)
    return np.asarray(v, dtype=np.float32)


class BruteForceKnnIndex:
    """Engine-side index: exact top-k by dense similarity scan.

    Mirrors brute_force_knn_integration.rs (mat_mul-based dense scan) but the
    scan runs through the jitted device kernel when available.
    """

    def __init__(
        self,
        metric: DistanceMetric,
        reserved_space: int = 0,
        dimensions: int | None = None,
        mesh=None,
    ):
        from pathway_tpu.ops import topk as topk_ops

        self.metric = metric
        self.mesh = mesh
        self._vectors: dict[int, np.ndarray] = {}
        self._filters: dict[int, Any] = {}
        self._dirty = True
        self._version = 0  # bumped on every change; keys the device cache
        self._keys: list[int] = []
        self._matrix: np.ndarray | None = None
        self._device_cache = topk_ops.DeviceIndexCache(mesh=mesh)

    def add(self, key: int, vector, filter_data=None) -> None:
        self._vectors[key] = _as_vec(vector)
        if filter_data is not None:
            self._filters[key] = filter_data
        self._dirty = True
        self._version += 1

    def remove(self, key: int) -> None:
        self._vectors.pop(key, None)
        self._filters.pop(key, None)
        self._dirty = True
        self._version += 1

    def _rebuild(self):
        self._keys = list(self._vectors.keys())
        if self._keys:
            self._matrix = np.stack([self._vectors[k] for k in self._keys])
        else:
            self._matrix = None
        self._dirty = False

    def search(self, query, k: int | None, filter_query=None) -> list[tuple[int, float]]:
        return self.search_many([(query, k, filter_query)])[0]

    def search_many(
        self, requests: list[tuple[Any, int | None, Any]]
    ) -> list[list[tuple[int, float]]]:
        """Answer a batch of ``(query, k, filter)`` requests in as few
        device dispatches as possible.

        The epoch's queries (``engine/dataflow.py:ExternalIndexNode``
        collects them) stack into one matrix per distinct fetch-k and run
        through the DeviceExecutor's bucketed top-k — one warm-compiled
        scan per epoch instead of one dispatch per query row."""
        if not requests:
            return []
        if self._dirty:
            self._rebuild()
        if self._matrix is None:
            return [[] for _ in requests]
        from pathway_tpu.ops import topk as topk_ops

        # group request positions by effective fetch-k (a filter means
        # over-fetch then post-filter on host)
        groups: dict[int, list[int]] = {}
        ks: list[int] = []
        for pos, (_q, k, filter_query) in enumerate(requests):
            k = 3 if k is None else k
            ks.append(k)
            fetch_k = (
                k
                if filter_query is None
                else min(len(self._keys), max(4 * k, 64))
            )
            groups.setdefault(fetch_k, []).append(pos)
        out: list[list[tuple[int, float]]] = [[] for _ in requests]
        for fetch_k, positions in groups.items():
            queries = np.stack([_as_vec(requests[p][0]) for p in positions])
            idx, scores = topk_ops.topk_search_cached(
                self._matrix,
                queries,
                fetch_k,
                self.metric.value,
                cache=self._device_cache,
                version=self._version,
            )
            for row, pos in enumerate(positions):
                k = ks[pos]
                filter_query = requests[pos][2]
                hits = []
                for i, score in zip(idx[row], scores[row]):
                    key = self._keys[int(i)]
                    if filter_query is not None and not metadata_matches(
                        filter_query, self._filters.get(key)
                    ):
                        continue
                    s = float(score)
                    # report distances for distance metrics (reference
                    # returns distance-like scores for L2, similarity for
                    # cos/ip)
                    hits.append(
                        (key, -s if self.metric == DistanceMetric.L2SQ else s)
                    )
                    if len(hits) >= k:
                        break
                out[pos] = hits
        return out


@dataclasses.dataclass
class _SimpleFactory:
    make: Callable[[], Any]

    def build(self):
        return self.make()


class BruteForceKnn(InnerIndex):
    """Exact KNN (parity: nearest_neighbors.py:170)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
        *,
        dimensions: int | None = None,
        reserved_space: int = 0,
        metric: BruteForceKnnMetricKind | DistanceMetric = DistanceMetric.COS,
        embedder=None,
        mesh=None,
    ):
        super().__init__(data_column, metadata_column)
        if isinstance(metric, BruteForceKnnMetricKind):
            metric = DistanceMetric(metric.value)
        self.metric = metric
        self.dimensions = dimensions
        self.embedder = embedder
        self.mesh = mesh

    def factory(self):
        metric = self.metric
        explicit_mesh = self.mesh

        def make():
            # late-bound so set_default_index_mesh() before pw.run() applies
            from pathway_tpu.parallel.mesh import get_default_index_mesh

            mesh = explicit_mesh if explicit_mesh is not None else get_default_index_mesh()
            return BruteForceKnnIndex(metric, mesh=mesh)

        return _SimpleFactory(make)

    def embed(self, column):
        if self.embedder is not None:
            return self.embedder(column)
        return column


class USearchKnn(BruteForceKnn):
    """Approximate KNN over an HNSW graph (parity: the reference's USearch
    index, nearest_neighbors.py:65 + usearch_integration.rs:163).

    Backed by the self-contained HNSW implementation in ``hnsw.py``; the
    USearch tuning parameters map directly: ``connectivity`` = M,
    ``expansion_add`` = efConstruction, ``expansion_search`` = ef."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
        *,
        dimensions: int | None = None,
        reserved_space: int = 0,
        metric: USearchMetricKind | DistanceMetric = DistanceMetric.COS,
        connectivity: int = 0,
        expansion_add: int = 0,
        expansion_search: int = 0,
        embedder=None,
        mesh=None,
    ):
        if isinstance(metric, USearchMetricKind):
            metric = DistanceMetric(metric.value)
        super().__init__(
            data_column,
            metadata_column,
            dimensions=dimensions,
            reserved_space=reserved_space,
            metric=metric,
            embedder=embedder,
            mesh=mesh,
        )
        self.connectivity = connectivity
        self.expansion_add = expansion_add
        self.expansion_search = expansion_search

    def factory(self):
        metric = self.metric
        connectivity = self.connectivity
        expansion_add = self.expansion_add
        expansion_search = self.expansion_search

        def make():
            from pathway_tpu.stdlib.indexing.hnsw import HnswIndex

            return HnswIndex(
                metric=metric.value,
                connectivity=connectivity,
                expansion_add=expansion_add,
                expansion_search=expansion_search,
            )

        return _SimpleFactory(make)




class LshKnnIndex:
    """Random-hyperplane LSH (analog of ml/classifiers/_knn_lsh.py)."""

    def __init__(self, dimensions: int, n_or: int = 4, n_and: int = 8, bucket_length: float = 10.0):
        self.dimensions = dimensions
        self.n_or = n_or
        self.n_and = n_and
        rng = np.random.default_rng(42)
        self._planes = [
            rng.normal(size=(n_and, dimensions)).astype(np.float32) for _ in range(n_or)
        ]
        self._buckets: list[dict[bytes, set[int]]] = [dict() for _ in range(n_or)]
        self._vectors: dict[int, np.ndarray] = {}
        self._filters: dict[int, Any] = {}

    def _hashes(self, v: np.ndarray) -> list[bytes]:
        return [
            np.packbits((p @ v) > 0).tobytes() for p in self._planes
        ]

    def add(self, key: int, vector, filter_data=None) -> None:
        v = _as_vec(vector)
        self._vectors[key] = v
        if filter_data is not None:
            self._filters[key] = filter_data
        for table, h in zip(self._buckets, self._hashes(v)):
            table.setdefault(h, set()).add(key)

    def remove(self, key: int) -> None:
        v = self._vectors.pop(key, None)
        self._filters.pop(key, None)
        if v is None:
            return
        for table, h in zip(self._buckets, self._hashes(v)):
            table.get(h, set()).discard(key)

    def search(self, query, k: int | None, filter_query=None) -> list[tuple[int, float]]:
        if k is None:
            k = 3
        q = _as_vec(query)
        candidates: set[int] = set()
        for table, h in zip(self._buckets, self._hashes(q)):
            candidates |= table.get(h, set())
        scored = []
        qn = np.linalg.norm(q) + 1e-12
        for key in candidates:
            if filter_query is not None and not metadata_matches(
                filter_query, self._filters.get(key)
            ):
                continue
            v = self._vectors[key]
            sim = float(q @ v / (qn * (np.linalg.norm(v) + 1e-12)))
            scored.append((key, sim))
        scored.sort(key=lambda e: -e[1])
        return scored[:k]


class LshKnn(InnerIndex):
    """LSH-backed approximate KNN (parity: nearest_neighbors.py:262)."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnReference | None = None,
        *,
        dimensions: int,
        n_or: int = 4,
        n_and: int = 8,
        bucket_length: float = 10.0,
        metric: DistanceMetric = DistanceMetric.COS,
        embedder=None,
    ):
        super().__init__(data_column, metadata_column)
        self.dimensions = dimensions
        self.n_or = n_or
        self.n_and = n_and
        self.bucket_length = bucket_length
        self.embedder = embedder

    def factory(self):
        dims, n_or, n_and, bl = self.dimensions, self.n_or, self.n_and, self.bucket_length
        return _SimpleFactory(lambda: LshKnnIndex(dims, n_or, n_and, bl))

    def embed(self, column):
        if self.embedder is not None:
            return self.embedder(column)
        return column
