"""Hybrid index — reciprocal-rank fusion (parity: stdlib/indexing/hybrid_index.py:14)."""

from __future__ import annotations

from collections import defaultdict
from typing import Any

from pathway_tpu.stdlib.indexing.data_index import InnerIndex


class _HybridEngineIndex:
    def __init__(self, inner_indexes, k: float = 60.0):
        self.inners = inner_indexes
        self.k = k

    def add(self, key: int, data, filter_data=None) -> None:
        # data is a tuple: one entry per inner index
        for inner, d in zip(self.inners, data):
            inner.add(key, d, filter_data)

    def remove(self, key: int) -> None:
        for inner in self.inners:
            inner.remove(key)

    def search(self, query, k: int | None, filter_query=None):
        if k is None:
            k = 3
        fused: dict[int, float] = defaultdict(float)
        for inner, q in zip(self.inners, query):
            results = inner.search(q, k * 3, filter_query)
            for rank, (key, _score) in enumerate(results):
                fused[key] += 1.0 / (self.k + rank + 1)
        ranked = sorted(fused.items(), key=lambda e: -e[1])
        return [(key, score) for key, score in ranked[:k]]


class HybridIndex(InnerIndex):
    """Fuses several inner indexes by reciprocal rank fusion.

    The engine-side data/query payloads are tuples with one element per
    sub-index (e.g. ``(embedding, text)`` for dense + BM25); ``embed`` and
    ``data_expr`` synthesize those tuples from each child's preparation.
    """

    def __init__(self, inner_indexes: list[InnerIndex], *, k: float = 60.0):
        super().__init__(inner_indexes[0].data_column, inner_indexes[0].metadata_column)
        self.inner_indexes = inner_indexes
        self.k = k

    def factory(self):
        factories = [ix.factory() for ix in self.inner_indexes]
        k = self.k

        class _F:
            @staticmethod
            def build():
                return _HybridEngineIndex([f.build() for f in factories], k)

        return _F()

    def embed(self, column):
        from pathway_tpu.internals.expression import make_tuple

        return make_tuple(*[ix.embed(column) for ix in self.inner_indexes])

    def data_expr(self, index_column):
        from pathway_tpu.internals.expression import make_tuple

        return make_tuple(
            *[ix.data_expr(index_column) for ix in self.inner_indexes]
        )


class HybridDataIndex:
    """Table-level hybrid index fusing several DataIndexes (RRF)."""

    def __new__(cls, data_table, data_indexes, *, k: float = 60.0):
        from pathway_tpu.stdlib.indexing.data_index import DataIndex

        inners = [di.inner_index for di in data_indexes]
        return DataIndex(data_table, HybridIndex(inners, k=k))


HybridIndexFactory = HybridIndex
