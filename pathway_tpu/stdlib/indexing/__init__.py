"""Index abstractions for retrieval (parity: stdlib/indexing/).

``DataIndex`` + inner indexes: BruteForceKnn (device top-k via ops/topk),
USearchKnn (API parity with the reference's HNSW index), TantivyBM25 analog
(host BM25), HybridIndex (reciprocal-rank fusion), LshKnn; retriever
factories for DocumentStore wiring.
"""

from pathway_tpu.stdlib.indexing.full_text_document_index import (
    default_full_text_document_index,
)
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    DistanceMetric,
    LshKnn,
    USearchKnn,
)
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25
from pathway_tpu.stdlib.indexing.hybrid_index import HybridDataIndex, HybridIndex
from pathway_tpu.stdlib.indexing.vector_document_index import (
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)
from pathway_tpu.stdlib.indexing.retrievers import (
    AbstractRetrieverFactory,
    BruteForceKnnFactory,
    BruteForceKnnMetricKind,
    HybridIndexFactory,
    TantivyBM25Factory,
    USearchMetricKind,
    UsearchKnnFactory,
    LshKnnFactory,
)

__all__ = [
    "DataIndex",
    "InnerIndex",
    "BruteForceKnn",
    "LshKnn",
    "USearchKnn",
    "DistanceMetric",
    "TantivyBM25",
    "HybridIndex",
    "HybridDataIndex",
    "default_full_text_document_index",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_lsh_knn_document_index",
    "default_usearch_knn_document_index",
    "AbstractRetrieverFactory",
    "BruteForceKnnFactory",
    "BruteForceKnnMetricKind",
    "HybridIndexFactory",
    "LshKnnFactory",
    "TantivyBM25Factory",
    "USearchMetricKind",
    "UsearchKnnFactory",
]
