"""Index abstractions for retrieval (parity: stdlib/indexing/).

``DataIndex`` + inner indexes: BruteForceKnn (device top-k via ops/topk),
USearchKnn (HNSW-style host graph index), TantivyBM25 analog (host BM25),
HybridIndex (reciprocal-rank fusion), LshKnn.
"""

from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    BruteForceKnnFactory,
    LshKnn,
    USearchKnn,
    USearchKnnFactory,
    DistanceMetric,
)
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25, TantivyBM25Factory
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex, HybridIndexFactory
from pathway_tpu.stdlib.indexing.vector_document_index import (
    default_brute_force_knn_document_index,
    default_lsh_knn_document_index,
    default_usearch_knn_document_index,
    default_vector_document_index,
)
from pathway_tpu.stdlib.indexing.retrievers import (
    AbstractRetrieverFactory,
    BruteForceKnnMetricKind,
    USearchMetricKind,
)

__all__ = [
    "DataIndex",
    "InnerIndex",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "LshKnn",
    "USearchKnn",
    "USearchKnnFactory",
    "DistanceMetric",
    "TantivyBM25",
    "TantivyBM25Factory",
    "HybridIndex",
    "HybridIndexFactory",
    "default_vector_document_index",
    "default_brute_force_knn_document_index",
    "default_lsh_knn_document_index",
    "default_usearch_knn_document_index",
    "AbstractRetrieverFactory",
    "BruteForceKnnMetricKind",
    "USearchMetricKind",
]
