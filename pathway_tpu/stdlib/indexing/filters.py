"""Metadata filter expressions for index queries.

Parity target: the JMESPath filters of ``src/external_integration/mod.rs``
(usearch/tantivy filter support).  Supports the operators the reference's
docs/templates use: ``==``/``!=`` comparisons on dotted paths, ``contains``,
``globmatch``, ``&&``/``||``/``!``.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any

from pathway_tpu.engine.types import Json


def _resolve_path(metadata: Any, path: str) -> Any:
    if isinstance(metadata, Json):
        metadata = metadata.value
    cur = metadata
    for part in path.split("."):
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    if isinstance(cur, Json):
        cur = cur.value
    return cur


_TOKEN = re.compile(
    r"\s*(&&|\|\||==|!=|>=|<=|>|<|\(|\)|!|,|'[^']*'|\"[^\"]*\"|[\w.`$@-]+)"
)


def _tokenize(s: str) -> list[str]:
    out, i = [], 0
    while i < len(s):
        m = _TOKEN.match(s, i)
        if not m:
            raise ValueError(f"bad filter syntax near {s[i:]!r}")
        out.append(m.group(1))
        i = m.end()
    return out


class _Parser:
    def __init__(self, tokens: list[str], metadata: Any):
        self.toks = tokens
        self.i = 0
        self.metadata = metadata

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def parse_or(self):
        v = self.parse_and()
        while self.peek() == "||":
            self.next()
            rhs = self.parse_and()
            v = v or rhs
        return v

    def parse_and(self):
        v = self.parse_not()
        while self.peek() == "&&":
            self.next()
            rhs = self.parse_not()
            v = v and rhs
        return v

    def parse_not(self):
        if self.peek() == "!":
            self.next()
            return not self.parse_not()
        return self.parse_cmp()

    def _value(self, tok: str):
        if tok and tok[0] in "'\"":
            return tok[1:-1]
        if tok == "null":
            return None
        if tok in ("true", "false"):
            return tok == "true"
        try:
            return int(tok)
        except ValueError:
            pass
        try:
            return float(tok)
        except ValueError:
            pass
        return _resolve_path(self.metadata, tok.strip("`"))

    def parse_cmp(self):
        if self.peek() == "(":
            self.next()
            v = self.parse_or()
            if self.next() != ")":
                raise ValueError("expected )")
            return v
        tok = self.next()
        if tok in ("contains", "globmatch", "starts_with", "ends_with"):
            if self.next() != "(":
                raise ValueError("expected (")
            a = self._value(self.next())
            if self.next() != ",":
                raise ValueError("expected ,")
            b = self._value(self.next())
            if self.next() != ")":
                raise ValueError("expected )")
            if tok == "contains":
                try:
                    return b in a if a is not None else False
                except TypeError:
                    return False
            if tok == "globmatch":
                return fnmatch.fnmatch(str(b or ""), str(a or ""))
            if tok == "starts_with":
                return str(a or "").startswith(str(b or ""))
            return str(a or "").endswith(str(b or ""))
        left = self._value(tok)
        op = self.peek()
        if op in ("==", "!=", ">", "<", ">=", "<="):
            self.next()
            right = self._value(self.next())
            try:
                if op == "==":
                    return left == right
                if op == "!=":
                    return left != right
                if op == ">":
                    return left > right
                if op == "<":
                    return left < right
                if op == ">=":
                    return left >= right
                return left <= right
            except TypeError:
                return False
        return bool(left)


def metadata_matches(filter_expression: str | None, metadata: Any) -> bool:
    """Evaluate a filter expression against one document's metadata
    (the JMESPath-style filter language of DocumentStore queries).

    Example:

    >>> from pathway_tpu.stdlib.indexing.filters import metadata_matches
    >>> meta = {"path": "/docs/a.pdf", "owner": "kim", "size": 4096}
    >>> metadata_matches("owner == 'kim'", meta)
    True
    >>> metadata_matches("size > 10000", meta)
    False
    >>> metadata_matches("globmatch('/docs/*.pdf', path) && owner == 'kim'", meta)
    True
    >>> metadata_matches(None, meta)  # no filter matches everything
    True
    """
    if filter_expression is None or filter_expression == "":
        return True
    if isinstance(filter_expression, Json):
        filter_expression = filter_expression.value
    try:
        return bool(_Parser(_tokenize(str(filter_expression)), metadata).parse_or())
    except ValueError:
        return False


__all__ = ["metadata_matches"]
