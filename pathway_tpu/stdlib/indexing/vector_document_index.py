"""Document-index factory helpers (parity:
stdlib/indexing/vector_document_index.py:34-157)."""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    DistanceMetric,
    LshKnn,
    USearchKnn,
)


def default_vector_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    return default_usearch_knn_document_index(
        data_column,
        data_table,
        embedder=embedder,
        dimensions=dimensions,
        metadata_column=metadata_column,
    )


def default_usearch_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    inner = USearchKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        metric=DistanceMetric.COS,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_brute_force_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    inner = BruteForceKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        metric=DistanceMetric.COS,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_lsh_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    inner = LshKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)
