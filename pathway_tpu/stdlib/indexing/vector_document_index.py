"""Document-index factory helpers (parity:
stdlib/indexing/vector_document_index.py:34-157)."""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    DistanceMetric,
    LshKnn,
    USearchKnn,
)


def default_vector_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    return default_usearch_knn_document_index(
        data_column,
        data_table,
        embedder=embedder,
        dimensions=dimensions,
        metadata_column=metadata_column,
    )


def default_usearch_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    inner = USearchKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        metric=DistanceMetric.COS,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_brute_force_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int | None = None,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    r"""Dense KNN document index over the device top-k path.

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.stdlib.indexing import default_brute_force_knn_document_index
    >>> from pathway_tpu.xpacks.llm.mocks import FakeEmbeddings
    >>> docs = pw.debug.table_from_markdown('''
    ... text
    ... apples_and_pears
    ... tpu_systolic_arrays
    ... ''')
    >>> index = default_brute_force_knn_document_index(
    ...     docs.text, docs, embedder=FakeEmbeddings(), dimensions=16
    ... )
    >>> queries = pw.debug.table_from_markdown('q\ntpu_systolic_arrays')
    >>> res = index.query_as_of_now(queries.q, number_of_matches=1).select(
    ...     match=pw.this.text
    ... )
    >>> pw.debug.compute_and_print(res, include_id=False)
    match
    ('tpu_systolic_arrays',)
    """
    inner = BruteForceKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        metric=DistanceMetric.COS,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)


def default_lsh_knn_document_index(
    data_column: ColumnReference,
    data_table: Table,
    *,
    embedder=None,
    dimensions: int,
    metadata_column: ColumnReference | None = None,
) -> DataIndex:
    inner = LshKnn(
        data_column,
        metadata_column,
        dimensions=dimensions,
        embedder=embedder,
    )
    return DataIndex(data_table, inner)
