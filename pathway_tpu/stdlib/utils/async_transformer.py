"""AsyncTransformer (parity: stdlib/utils/async_transformer.py:30-).

Non-blocking async row transformer: results form a *new* stream, decoupled
from input epochs (§3.3 of SURVEY.md).  In this engine the invoke results
re-enter through a dedicated InputNode at later timestamps.
"""

from __future__ import annotations

import asyncio
from typing import Any

from pathway_tpu.engine import dataflow as df
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Lowerer, Table, Universe


class AsyncTransformer:
    r"""Subclass and implement ``async def invoke(self, **kwargs) -> dict``.

    ``output_schema`` must be declared as a class attribute or passed to
    ``__init__``; ``.successful`` gives the result table.

    Example:

    >>> import pathway_tpu as pw
    >>> from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer
    >>> class Upper(AsyncTransformer):
    ...     output_schema = pw.schema_from_types(out=str)
    ...     async def invoke(self, w):
    ...         return {"out": w.upper()}
    >>> t = pw.debug.table_from_markdown('w\nhi\nyo')
    >>> res = Upper(input_table=t).successful
    >>> pw.debug.compute_and_print(res, include_id=False)
    out
    HI
    YO
    """

    output_schema: type[schema_mod.Schema] | None = None

    def __init__(self, input_table: Table, *, instance=None, autocommit_duration_ms=1500, name=None):
        self._input_table = input_table
        if self.output_schema is None:
            raise ValueError("AsyncTransformer requires output_schema")
        self._result_table = self._make_result_table()

    def open(self) -> None:  # lifecycle hooks (parity)
        pass

    def close(self) -> None:
        pass

    async def invoke(self, **kwargs) -> dict:
        raise NotImplementedError

    @property
    def successful(self) -> Table:
        return self._result_table

    @property
    def output_table(self) -> Table:
        return self._result_table

    def with_options(self, **kwargs) -> "AsyncTransformer":
        return self

    def _make_result_table(self) -> Table:
        schema = self.output_schema
        names = list(schema.__columns__.keys())
        input_table = self._input_table
        in_names = input_table.column_names()
        transformer = self

        def build(lowerer: Lowerer) -> df.Node:
            in_node = lowerer.node(input_table)
            out_node = df.InputNode(lowerer.scope)
            out_node.finished = False
            pending: list = []

            class _Feeder(df.Node):
                name = "async_transformer_feed"

                def step(self_inner, time):
                    for key, row, diff in self_inner.take_pending():
                        if diff > 0:
                            pending.append((key, row))

            feeder = _Feeder(lowerer.scope, [in_node])

            class _Poller:
                def __init__(self):
                    self.opened = False
                    self.source_done = False

                def poll(self) -> bool:
                    if not self.opened:
                        transformer.open()
                        self.opened = True
                    if pending:
                        batch, pending_clear = list(pending), pending.clear()

                        async def run_batch():
                            coros = []
                            for key, row in batch:
                                kwargs = dict(zip(in_names, row))
                                coros.append(transformer.invoke(**kwargs))
                            return await asyncio.gather(*coros, return_exceptions=True)

                        results = asyncio.run(run_batch())
                        t = lowerer.scope.current_time + 2
                        for (key, row), res in zip(batch, results):
                            if isinstance(res, Exception):
                                continue  # failed rows are dropped (parity: .failed)
                            out_row = tuple(res.get(n) for n in names)
                            out_node.insert(key, out_row, t)
                        return False
                    # finished when the upstream scope has no more input
                    if all(
                        inp.finished
                        for inp in lowerer.scope.nodes
                        if isinstance(inp, df.InputNode) and inp is not out_node
                    ) and not pending:
                        out_node.finished = True
                        transformer.close()
                        return True
                    return False

            lowerer.pollers.append(_Poller())
            return out_node

        return Table(schema, build, universe=Universe())
