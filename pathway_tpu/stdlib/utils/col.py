"""Column utilities (parity: stdlib/utils/col.py)."""

from __future__ import annotations

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


def unpack_col(column: ColumnReference, *unpacked_columns, schema=None) -> Table:
    """Unpack a tuple column into named columns."""
    table = column.table
    if schema is not None:
        names = list(schema.__columns__.keys())
    else:
        names = [
            c.name if isinstance(c, ColumnReference) else str(c)
            for c in unpacked_columns
        ]
    exprs = {}
    for i, n in enumerate(names):
        exprs[n] = expr_mod.ApplyExpression(
            lambda t, _i=i: t[_i], None, column
        )
    return table.select(**exprs)


def flatten_column(column: ColumnReference, origin_id: str | None = "origin_id") -> Table:
    table = column.table
    return table.flatten(column, origin_id=origin_id)


__all__ = ["unpack_col", "flatten_column"]
