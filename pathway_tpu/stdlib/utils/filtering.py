"""Row-filtering helpers (parity: stdlib/utils/filtering.py)."""

from __future__ import annotations

from pathway_tpu.internals import reducers
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table


def _arg_rows(table: Table, *on, reducer) -> Table:
    grouped = table.groupby(*on[1:]) if len(on) > 1 else table.groupby()
    picked = grouped.reduce(_pw_pick=reducer(on[0]))
    keyed = picked.with_id(ColumnReference(None, "_pw_pick")) if False else picked
    from pathway_tpu.internals.thisclass import this

    keyed = picked.with_id(this._pw_pick)
    return table.restrict(keyed)


def argmax_rows(table: Table, *on, what) -> Table:
    """Keep, per group of ``on[1:]`` columns, the row maximizing ``what``."""
    from pathway_tpu.internals.thisclass import this

    grouped = table.groupby(*on) if on else table.groupby()
    picked = grouped.reduce(_pw_pick=reducers.argmax(what))
    keyed = picked.with_id(this._pw_pick)
    return table.restrict(keyed)


def argmin_rows(table: Table, *on, what) -> Table:
    from pathway_tpu.internals.thisclass import this

    grouped = table.groupby(*on) if on else table.groupby()
    picked = grouped.reduce(_pw_pick=reducers.argmin(what))
    keyed = picked.with_id(this._pw_pick)
    return table.restrict(keyed)


__all__ = ["argmax_rows", "argmin_rows"]
