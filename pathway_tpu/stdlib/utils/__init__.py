"""stdlib.utils (parity: stdlib/utils/): col helpers, filtering, bucketing,
AsyncTransformer, pandas_transformer."""

from pathway_tpu.stdlib.utils.col import unpack_col, flatten_column
from pathway_tpu.stdlib.utils.filtering import argmax_rows, argmin_rows
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer

__all__ = [
    "unpack_col",
    "flatten_column",
    "argmax_rows",
    "argmin_rows",
    "AsyncTransformer",
    "pandas_transformer",
]
