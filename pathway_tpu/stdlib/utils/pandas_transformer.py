"""``@pw.pandas_transformer`` (parity: stdlib/utils/pandas_transformer.py).

Runs a pandas function over full (static) tables — the reference implements
it via ``apply`` over packed columns; here the capture/rebuild round-trips
through the debug helpers.
"""

from __future__ import annotations

import functools
from typing import Callable

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table


def pandas_transformer(output_schema: type[schema_mod.Schema], output_universe=None):
    def decorator(func: Callable) -> Callable:
        @functools.wraps(func)
        def wrapper(*tables: Table) -> Table:
            import pathway_tpu.debug as dbg

            dfs = [dbg.table_to_pandas(t, include_id=False) for t in tables]
            result_df = func(*dfs)
            return dbg.table_from_pandas(result_df, schema=output_schema)

        return wrapper

    return decorator


__all__ = ["pandas_transformer"]
