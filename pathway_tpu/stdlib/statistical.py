"""Statistical helpers (parity: stdlib/statistical: interpolate)."""

from __future__ import annotations

import enum

from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals.expression import ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.thisclass import this


class InterpolateMode(enum.Enum):
    LINEAR = 0


def interpolate(
    table: Table, timestamp, *values, mode: InterpolateMode = InterpolateMode.LINEAR
) -> Table:
    r"""Linear interpolation of missing values along the timestamp order
    (parity: stdlib/statistical/interpolate).

    Example:

    >>> import pathway_tpu as pw
    >>> t = pw.debug.table_from_markdown('t | v\n0 | 0.0\n2 |\n4 | 4.0')
    >>> r = pw.statistical.interpolate(t, pw.this.t, pw.this.v)
    >>> pw.debug.compute_and_print(r, include_id=False)
    t | v
    0 | 0.0
    2 | 2.0
    4 | 4.0
    """
    sorted_t = table.sort(key=timestamp)
    t_name = timestamp.name if isinstance(timestamp, ColumnReference) else "_t"

    exprs = {}
    for v in values:
        name = v.name if isinstance(v, ColumnReference) else str(v)

        def make_interp(col_name):
            def interp(cur_val, prev_t, prev_v, next_t, next_v, cur_t):
                if cur_val is not None:
                    return cur_val
                if prev_v is None and next_v is None:
                    return None
                if prev_v is None:
                    return next_v
                if next_v is None:
                    return prev_v
                if next_t == prev_t:
                    return prev_v
                frac = (cur_t - prev_t) / (next_t - prev_t)
                return prev_v + (next_v - prev_v) * frac

            return interp

        prev_view = table.ix(sorted_t.prev, optional=True)
        next_view = table.ix(sorted_t.next, optional=True)
        exprs[name] = expr_mod.ApplyExpression(
            make_interp(name),
            None,
            getattr(this, name),
            getattr(prev_view, t_name),
            getattr(prev_view, name),
            getattr(next_view, t_name),
            getattr(next_view, name),
            getattr(this, t_name),
            _propagate_none=False,
        )
    return table.with_columns(**exprs)


__all__ = ["interpolate", "InterpolateMode"]
