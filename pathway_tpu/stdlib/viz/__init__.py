"""Live table/plot visualization (parity: python/pathway/stdlib/viz/).

``Table.show()`` / ``Table.plot()`` / ``_repr_mimebundle_`` — jupyter
widgets that preview a bounded table immediately and auto-update a
streaming one after ``pw.run()``.

The reference builds panel+bokeh dashboards.  Neither wheel ships in
this image, so: with ``panel``/``bokeh`` importable the same widget
shapes are produced; without them ``show`` degrades to a pandas snapshot
(static) or a subscriber-fed snapshot object (streaming), and ``plot``
raises the gating ImportError the other optional integrations use.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals.table import Table


def _optional_panel():
    try:
        import bokeh  # noqa: F401
        import panel

        return panel
    except ImportError:
        return None


class TableSnapshot:
    """Fallback widget: maintains a keyed snapshot fed by a subscriber."""

    def __init__(self, table: Table, include_id: bool, snapshot_only: bool):
        self.table = table
        self.include_id = include_id
        self.snapshot_only = snapshot_only
        self.rows: dict = {}
        self.changes: list = []

    def _update(self, key, row, time, diff):
        if diff > 0:
            self.rows[key] = row
        else:
            self.rows.pop(key, None)
        self.changes.append((key, row, time, diff))

    def to_pandas(self):
        import pandas as pd

        names = list(self.table.column_names())
        if self.snapshot_only:
            data = [
                ((key,) if self.include_id else ()) + tuple(row)
                for key, row in sorted(self.rows.items())
            ]
            cols = (["id"] if self.include_id else []) + names
        else:
            data = [
                ((key,) if self.include_id else ()) + tuple(row) + (time, diff)
                for key, row, time, diff in self.changes
            ]
            cols = (["id"] if self.include_id else []) + names + ["time", "diff"]
        return pd.DataFrame(data, columns=cols)

    def _repr_html_(self):
        return self.to_pandas()._repr_html_()


def show(
    self: Table,
    *,
    snapshot: bool = True,
    include_id: bool = True,
    short_pointers: bool = True,
    sorters: Any = None,
) -> Any:
    """Display the table in a notebook; streaming tables update on pw.run().

    Reference: ``stdlib/viz/table_viz.py:26`` (panel Tabulator column).
    """
    panel = _optional_panel()
    widget = TableSnapshot(self, include_id, snapshot_only=snapshot)
    self._subscribe_raw(widget._update, name="viz:show")
    if panel is None:
        return widget
    import pandas as pd

    tabulator = panel.widgets.Tabulator(
        pd.DataFrame(), disabled=True, show_index=False
    )

    def refresh(*_a):
        tabulator.value = widget.to_pandas()

    self._subscribe_raw(
        lambda key, row, time, diff: refresh(), name="viz:show:refresh"
    )
    return panel.Column(tabulator)


def plot(
    self: Table,
    plotting_function: Callable[..., Any],
    sorting_col: str | None = None,
) -> Any:
    """Bokeh plot over the table, streamed via a ColumnDataSource.

    Reference: ``stdlib/viz/plotting.py:35``.
    """
    panel = _optional_panel()
    if panel is None:
        raise ImportError(
            "Table.plot requires the optional 'panel' and 'bokeh' packages, "
            "which are not installed in this environment"
        )
    from bokeh.models import ColumnDataSource

    names = list(self.column_names())
    source = ColumnDataSource(data={n: [] for n in names})
    figure = plotting_function(source)
    widget = TableSnapshot(self, include_id=False, snapshot_only=True)

    def refresh(key, row, time, diff):
        widget._update(key, row, time, diff)
        df = widget.to_pandas()
        if sorting_col:
            df = df.sort_values(sorting_col)
        source.stream(df.to_dict("list"), rollover=len(df))

    self._subscribe_raw(refresh, name="viz:plot")
    return panel.Column(figure)


def _repr_mimebundle_(self: Table, include, exclude):
    return {"text/html": show(self)._repr_html_()}


Table.show = show  # type: ignore[attr-defined]
Table.plot = plot  # type: ignore[attr-defined]

__all__ = ["plot", "show", "_repr_mimebundle_", "TableSnapshot"]
